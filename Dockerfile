# Serving image (reference: /root/reference/Dockerfile — python:3.11-slim +
# uvicorn). The TPU build ships the whole package and runs the aiohttp
# entrypoint; on TPU VMs use a jax[tpu]-enabled base instead.
FROM python:3.11-slim

ENV PYTHONDONTWRITEBYTECODE=1 \
    PYTHONUNBUFFERED=1

WORKDIR /app

COPY requirements.txt .
RUN pip install --no-cache-dir -r requirements.txt

COPY ai_agent_kubectl_tpu/ ai_agent_kubectl_tpu/

EXPOSE 8000

CMD ["python", "-m", "ai_agent_kubectl_tpu.server"]
