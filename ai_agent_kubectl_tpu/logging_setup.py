"""Logging configuration (reference app.py:38-47) + structured JSON mode.

``LOG_FORMAT=text`` (default) keeps the reference's human format.
``LOG_FORMAT=json`` emits one JSON object per line — timestamp, level,
logger, message, and the active request ID from the trace context
(obs/trace.py) — so a slow request found in the flight recorder and its
log lines meet on the same ``request_id`` key. The request-ID filter is
installed in BOTH modes (text lines append a ``[rid]`` suffix when a
trace is active), because the ID is what makes a 3 am log excerpt
actionable.
"""

from __future__ import annotations

import datetime
import json
import logging

from .engine.qos import current_qos
from .obs.incidents import current_incident_id
from .obs.ledger import hash_tenant
from .obs.trace import current_trace


class RequestIdFilter(logging.Filter):
    """Stamp every record with the active request's ID (or None), plus
    its QoS classification: the lane verbatim (a closed three-value
    set) and the tenant HASHED (obs/ledger.py hash_tenant — the raw key
    may be an API key, and the hash is the same form the goodput
    ledger's /debug/ledger tenant table uses, so a log grep and a
    ledger row join on one opaque key).

    A Filter rather than a Formatter concern so ``record.request_id``
    exists even for records a third-party formatter renders."""

    def filter(self, record: logging.LogRecord) -> bool:
        trace = current_trace()
        record.request_id = trace.request_id if trace is not None else None
        qctx = current_qos()
        record.tenant = hash_tenant(qctx.tenant) if qctx is not None \
            else None
        record.lane = qctx.lane if qctx is not None else None
        # Incident join (ISSUE 15): while an incident's stamp window is
        # open, every line carries its id — the same join pattern as
        # the hashed tenant, so a /debug/incidents bundle and a log
        # grep meet on one key post-hoc.
        record.incident_id = current_incident_id()
        return True


class JsonFormatter(logging.Formatter):
    """One JSON object per line; stdlib only."""

    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": datetime.datetime.fromtimestamp(
                record.created, tz=datetime.timezone.utc
            ).isoformat(),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "request_id": getattr(record, "request_id", None),
            # QoS classification (ISSUE 8): hashed tenant + lane, so log
            # lines join against the goodput ledger's tenant table.
            "tenant": getattr(record, "tenant", None),
            "lane": getattr(record, "lane", None),
            # Incident join (ISSUE 15): non-None while an incident's
            # stamp window is open — grep for it to collect the lines
            # around a /debug/incidents bundle.
            "incident_id": getattr(record, "incident_id", None),
        }
        if record.exc_info:
            entry["exc_info"] = self.formatException(record.exc_info)
        # default=repr: a bad interpolation argument must never take the
        # logging pipeline down with a serialization error.
        return json.dumps(entry, default=repr)


class TextFormatter(logging.Formatter):
    """Reference format, plus a ``[rid]`` suffix when a trace is active."""

    def __init__(self) -> None:
        super().__init__("%(asctime)s - %(name)s - %(levelname)s - %(message)s")

    def format(self, record: logging.LogRecord) -> str:
        line = super().format(record)
        rid = getattr(record, "request_id", None)
        return f"{line} [{rid}]" if rid else line


def setup_logging(level: str = "INFO", fmt: str = "text") -> logging.Logger:
    handler = logging.StreamHandler()
    handler.addFilter(RequestIdFilter())
    handler.setFormatter(JsonFormatter() if fmt == "json" else TextFormatter())
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        handlers=[handler],
        force=True,
    )
    return logging.getLogger("ai_agent_kubectl_tpu")


def startup_warnings(cfg) -> None:
    """Key-presence warnings at startup (reference app.py:42-47)."""
    logger = logging.getLogger("ai_agent_kubectl_tpu")
    if not cfg.api_auth_key:
        logger.warning(
            "API_AUTH_KEY environment variable not set. API authentication is disabled."
        )
    if cfg.engine == "openai" and not cfg.openai_api_key:
        logger.error(
            "ENGINE=openai but OPENAI_API_KEY not set; engine will run degraded (503)."
        )
    if not cfg.debug_token:
        logger.info(
            "DEBUG_TOKEN not set: /debug/* endpoints are guarded only by "
            "API-key auth (when enabled)."
        )
