"""Logging configuration matching the reference's format (app.py:38-47)."""

from __future__ import annotations

import logging


def setup_logging(level: str = "INFO") -> logging.Logger:
    logging.basicConfig(
        level=getattr(logging, level.upper(), logging.INFO),
        format="%(asctime)s - %(name)s - %(levelname)s - %(message)s",
    )
    return logging.getLogger("ai_agent_kubectl_tpu")


def startup_warnings(cfg) -> None:
    """Key-presence warnings at startup (reference app.py:42-47)."""
    logger = logging.getLogger("ai_agent_kubectl_tpu")
    if not cfg.api_auth_key:
        logger.warning(
            "API_AUTH_KEY environment variable not set. API authentication is disabled."
        )
    if cfg.engine == "openai" and not cfg.openai_api_key:
        logger.error(
            "ENGINE=openai but OPENAI_API_KEY not set; engine will run degraded (503)."
        )
