"""ai_agent_kubectl_tpu — a TPU-native natural-language → kubectl framework.

A ground-up rebuild of the capabilities of ``mrankitvish/ai-agent-kubectl``
(reference: ``/root/reference/app.py``): an HTTP service that translates
natural-language queries into validated single-line ``kubectl`` commands and
optionally executes them — with the reference's remote OpenAI ChatCompletion
call replaced by an in-tree JAX/XLA/Pallas inference engine running entirely
on TPU.

Package layout:

- ``config``    — typed env-var configuration (reference: app.py:23-36)
- ``server``    — HTTP API, auth, rate limiting, caching, metrics, execution
                  (reference: app.py:60-400)
- ``engine``    — the inference engine that replaces the remote LLM call
                  (reference seam: app.py:117,184): tokenizer, KV caches,
                  batching scheduler, jit prefill/decode
- ``models``    — pure-JAX decoder-only transformer families (Gemma, Llama,
                  Mixtral) and weight conversion
- ``ops``       — Pallas TPU kernels (flash attention, paged decode
                  attention, ring attention) and numeric reference ops
- ``parallel``  — device mesh construction, NamedSharding policies (DP/TP/
                  EP/SP), multi-host initialization
- ``utils``     — profiling, watchdog, misc
"""

__version__ = "0.1.0"
