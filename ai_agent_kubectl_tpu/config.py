"""Typed environment-variable configuration.

Rebuilds the reference's config layer (app.py:23-36, .env-sample:1-25) as a
frozen dataclass parsed once at startup. Every reference knob is preserved
verbatim (``API_AUTH_KEY``, ``CACHE_MAXSIZE``, ``CACHE_TTL``, ``LLM_TIMEOUT``,
``EXECUTION_TIMEOUT``, ``RATE_LIMIT``, ``LOG_LEVEL``, ``PORT``, ``HOST``).
The reference's ``OPENAI_*`` knobs are replaced by local-engine knobs
(``MODEL_NAME``, ``MODEL_PATH``, mesh/dtype/sequence/batch settings); an
OpenAI-compatible client engine is still available for parity with the
reference's remote path (``ENGINE=openai``, honouring ``OPENAI_BASE_URL``).

A minimal ``.env`` loader replaces python-dotenv (reference app.py:24): lines
of ``KEY=VALUE``, ``#`` comments, optional ``export`` prefix, single/double
quote stripping. Existing process env always wins (dotenv semantics).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field, fields
from pathlib import Path
from typing import Optional, Tuple


def load_env_file(path: str | os.PathLike = ".env", *, override: bool = False) -> dict:
    """Parse a .env file into os.environ. Returns the parsed mapping.

    Missing file is not an error (matches dotenv behaviour the reference
    relies on at app.py:24).
    """
    parsed: dict[str, str] = {}
    p = Path(path)
    if not p.is_file():
        return parsed
    for raw in p.read_text().splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("export "):
            line = line[len("export "):].lstrip()
        if "=" not in line:
            continue
        key, _, value = line.partition("=")
        key = key.strip()
        value = value.strip()
        if len(value) >= 2 and value[0] == value[-1] and value[0] in ("'", '"'):
            value = value[1:-1]
        else:
            # Strip trailing inline comment on unquoted values.
            value = value.split(" #", 1)[0].rstrip()
        if not key:
            continue
        parsed[key] = value
        if override or key not in os.environ:
            os.environ[key] = value
    return parsed


_RATE_RE = re.compile(
    r"^\s*(\d+)\s*(?:/|\s+per\s+)\s*(\d*)\s*(second|minute|hour|day)s?\s*$",
    re.IGNORECASE,
)

_PERIOD_SECONDS = {"second": 1, "minute": 60, "hour": 3600, "day": 86400}


def parse_rate_limit(spec: str) -> Tuple[int, float]:
    """Parse a slowapi-style rate string ("10/minute", "5 per 30 second")
    into (count, window_seconds). Reference default: "10/minute"
    (app.py:32)."""
    m = _RATE_RE.match(spec)
    if not m:
        raise ValueError(f"Invalid rate limit spec: {spec!r}")
    count = int(m.group(1))
    multiple = int(m.group(2)) if m.group(2) else 1
    window = multiple * _PERIOD_SECONDS[m.group(3).lower()]
    return count, float(window)


def _mesh_device_count(spec: str) -> int:
    """Device count a MESH_SHAPE/DCN_MESH_SHAPE spec asks for (product
    of its axis sizes; 1 for empty). A jax-free mirror of
    parallel/mesh.py::MeshConfig.parse's arithmetic — config validation
    must not import jax (the fake/openai deployments stay jax-free),
    and malformed axis names are the engine's error to raise, so
    unknown parts simply count their integer value."""
    total = 1
    for part in filter(None, (p.strip() for p in (spec or "").split(","))):
        _, _, val = part.replace(":", "=").partition("=")
        try:
            total *= max(1, int(val))
        except ValueError:
            continue
    return total


#: jax-free mirror of parallel/mesh.py::MeshConfig.parse's alias map —
#: config validation must not import jax (the fake/openai deployments
#: stay jax-free), but the spec-decode capability check (ISSUE 18)
#: needs to know WHICH axes a mesh spec scales, not just how many
#: devices it asks for.
_MESH_AXIS_ALIASES = {
    "dp": "data", "data": "data",
    "ep": "expert", "expert": "expert",
    "pp": "pipe", "pipe": "pipe",
    "sp": "seq", "seq": "seq",
    "tp": "model", "model": "model",
}

#: axes speculative decoding cannot serve under: the spec pool's blocks
#: are a shared cross-slot structure (never shard over data/pipe/seq)
#: and the draft stack rides the mesh whole (no pipeline split).
_SPEC_UNSHARDABLE_AXES = frozenset({"data", "pipe", "seq"})


def _mesh_unshardable_axes(spec: str) -> set:
    """Canonical names of >1 data/pipe/seq axes a MESH_SHAPE /
    DCN_MESH_SHAPE spec asks for — the combinations SPEC_DECODE refuses
    (ISSUE 18). Unknown axis names are the engine's error to raise and
    are ignored here, mirroring ``_mesh_device_count``."""
    out = set()
    for part in filter(None, (p.strip() for p in (spec or "").split(","))):
        name, _, val = part.replace(":", "=").partition("=")
        canon = _MESH_AXIS_ALIASES.get(name.strip().lower())
        try:
            size = int(val)
        except ValueError:
            continue
        if canon in _SPEC_UNSHARDABLE_AXES and size > 1:
            out.add(canon)
    return out


def _env_str(name: str, default: Optional[str]) -> Optional[str]:
    v = os.getenv(name)
    return v if v not in (None, "") else default


def _env_int(name: str, default: int) -> int:
    v = os.getenv(name)
    return int(v) if v not in (None, "") else default


def _env_float(name: str, default: float) -> float:
    v = os.getenv(name)
    return float(v) if v not in (None, "") else default


def _env_bool(name: str, default: bool) -> bool:
    v = os.getenv(name)
    if v in (None, ""):
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


@dataclass(frozen=True)
class ServiceConfig:
    """Everything the serving layer needs; reference knobs preserved."""

    # --- reference knobs, verbatim (app.py:27-33, 394-395) ---
    api_auth_key: Optional[str] = None      # API_AUTH_KEY; auth disabled if unset
    cache_maxsize: int = 100                # CACHE_MAXSIZE
    cache_ttl: float = 300.0                # CACHE_TTL seconds
    llm_timeout: float = 60.0               # LLM_TIMEOUT seconds
    execution_timeout: float = 30.0         # EXECUTION_TIMEOUT seconds
    rate_limit: str = "10/minute"           # RATE_LIMIT
    log_level: str = "INFO"                 # LOG_LEVEL
    # Log line format: "text" keeps the reference's human format; "json"
    # emits one JSON object per line, stamped with the active request ID
    # (obs/trace.py) so a flight-recorder lookup and a log grep meet on
    # the same key.
    log_format: str = "text"                # LOG_FORMAT: text | json
    host: str = "0.0.0.0"                   # HOST
    port: int = 8000                        # PORT
    # Honour X-Forwarded-For for rate-limit keying ONLY behind a trusted
    # proxy — a direct client could otherwise mint a fresh quota per request.
    trust_proxy_headers: bool = False       # TRUST_PROXY_HEADERS

    # --- engine selection (replaces OPENAI_* block, app.py:34-36) ---
    engine: str = "jax"                     # ENGINE: jax | jax-batched | fake | openai
                                            #   "jax" serves through the continuous-
                                            #   batching scheduler when
                                            #   DECODE_BATCH_SIZE > 1 (the default)
    model_name: str = "toy-8m"              # MODEL_NAME (registry key)
    model_path: Optional[str] = None        # MODEL_PATH (checkpoint dir)
    tokenizer_path: Optional[str] = None    # TOKENIZER_PATH

    # --- engine knobs ---
    dtype: str = "bfloat16"                 # DTYPE
    # Weight-only quantization: int8 (ops/quant.py) halves projection
    # weight bytes; int4 (ops/quant4.py, Pallas packed-nibble matmul,
    # group-wise scales) halves them again — decode is weight-read-bound,
    # so near-proportional throughput for large dense models. int4 is
    # single-chip only (falls back to int8 under a mesh). "" disables.
    quant: str = ""                         # QUANT: "" | int8 | int4
    # int8 KV cache (ops/quant.py::QuantKV): halves the KV pool and the
    # per-step decode-attention HBM read — on HBM-capped single-chip
    # serving (7B-class) this doubles the decode batch that fits beside
    # the weights. Composes with every mesh axis incl. pipe (the stage
    # bodies tree-map QuantKV); DECODE_ATTN=paged falls back to the dense
    # ladder (the paged kernel reads bf16 KV).
    kv_quant: str = ""                      # KV_QUANT: "" | int8
    max_seq_len: int = 1024                 # MAX_SEQ_LEN
    max_new_tokens: int = 128               # MAX_NEW_TOKENS
    decode_batch_size: int = 8              # DECODE_BATCH_SIZE (continuous batching slots)
    # Decode-chunk length: tokens generated per jitted chunk dispatch.
    # Larger chunks amortize dispatch overhead but admit new requests at
    # coarser granularity (TTFT under load). 16 is the bench-proven value
    # (chunk 32 measured -15% throughput and 2x TTFT; BENCH_r04).
    chunk_len: int = 16                     # CHUNK_LEN
    # Speculative decode chunks kept in flight ahead of the consumer.
    # With device-side termination (the done mask in the decode chunk's
    # carry — see DEVICE_TERMINATION) a deeper pipe no longer wastes a
    # speculative chunk per finished request, so the default is 3: the
    # consumer stays two fetch RTTs ahead of the device, which a ~100 ms
    # tunnel RTT against a ~33 ms 7B chunk needs for serving throughput
    # to track the device ceiling. Depth 2 was the old default (and
    # remains the right choice with DEVICE_TERMINATION=false).
    chunk_pipe_depth: int = 3               # CHUNK_PIPE_DEPTH
    # Device-resident request termination: the decode chunk compares each
    # sampled token against the EOS set and the per-slot max_tokens
    # budget INSIDE the jitted scan, freezes finished slots mid-chunk
    # (no further sampling/KV writes), and returns one packed buffer
    # [tokens, done_mask, live_lengths, n_alive] per chunk — one fetch
    # carries tokens AND termination, so the scheduler retires slots at
    # consume time instead of after a host-side EOS scan. false restores
    # the host-scan path (A/B comparisons; wasted_decode_steps_total then
    # shows what the mask saves).
    device_termination: bool = True         # DEVICE_TERMINATION
    prefill_buckets: str = "64,128,256,512,1024"  # PREFILL_BUCKETS (padded prefill shapes)
    temperature: float = 0.0                # TEMPERATURE (0 == greedy, matches app.py:109)
    # Sampling filters (apply when TEMPERATURE > 0): TOP_K keeps the k
    # highest logits (0 disables); TOP_P nucleus sampling (1.0 disables).
    # Static service config — both engines sample from the same filtered
    # distribution at the same settings (engine/sampling.py).
    top_k: int = 0                          # TOP_K
    top_p: float = 1.0                      # TOP_P
    attn_impl: str = "auto"                 # ATTN_IMPL: auto | dense | flash (prefill kernel)
    # Decode attention: "paged" reads only each slot's live KV pages
    # (ops/paged_attention.py). "auto" picks paged for GQA models on TPU
    # (measured 2.08x on Llama-3-8B bs=32, raising KV_PAGE_SIZE to >= 64)
    # and dense-over-KV-bucket for MQA/MHA (faster there, measured).
    decode_attn: str = "auto"               # DECODE_ATTN: auto | dense | paged
    # MoE dispatch: "auto" uses expert-parallel all-to-all dispatch when
    # the mesh has expert>1, dense all-experts otherwise; "ep" forces the
    # dispatch path (a 1-device expert mesh is built if needed — how one
    # chip serves the real EP program); "dense" forces all-experts.
    moe_impl: str = "auto"                  # MOE_IMPL: auto | ep | dense
    kv_page_size: int = 16                  # KV_PAGE_SIZE (paged attention)
    # Ragged paged attention (ISSUE 19; ops/ragged_attention.py): ONE
    # kernel over the block pool serves decode (q_len=1), spec verify
    # (q_len=k+1), and admission suffix prefill (q_len=prompt-span), so
    # a mixed prefill+decode+verify chunk is one program dispatch and
    # the (bucket, kv_limit) pool-prefill program ladder collapses.
    # "auto" = on in pool mode on TPU (CPU keeps the legacy ladder —
    # interpret-mode Pallas has a different cost model); "off" = the
    # legacy three-regime world for A/B. Falls back loudly (the
    # attention_regime health field / decode_attention_regime gauge)
    # when KV is int8-quantized or KV heads don't divide the model
    # axis.
    ragged_attention: str = "auto"          # RAGGED_ATTENTION: auto | on | off
    # --- block-paged KV pool + radix prefix sharing (ISSUE 10) ---
    # Replace per-slot dense KV (every request owning an S_alloc-row
    # region — the thing that capped the batch at bs=64 on 7B int8) with
    # one shared [n_blocks, page, KV, hd] pool per layer + per-slot
    # block tables: a slot holds only the pages its live span needs, so
    # the same HBM admits ~S_alloc/avg_len x the slots (bs≈192+ on the
    # 8B geometry). false = the dense KV ladder (A/B; also the automatic
    # fallback under a serving mesh — pool TP sharding is ROADMAP 4).
    kv_pool: bool = True                    # KV_POOL
    # Pool page (tokens per block). Must divide the 128-token kv-limit
    # tile so every gather width is a whole page count; DECODE_ATTN=auto
    # raises it to 64 on TPU (smaller pages are grid-overhead-bound).
    kv_pool_page: int = 16                  # KV_POOL_PAGE
    # Total pool blocks. 0 = auto: batch_size x pages-per-slot — the
    # dense HBM envelope, which sharing then oversubscribes. Sizing it
    # below auto oversubscribes explicitly: admission keeps working
    # until genuinely out (radix eviction reclaims cached blocks first),
    # then slots truncate at their current length instead of corrupting.
    kv_pool_blocks: int = 0                 # KV_POOL_BLOCKS
    # Radix-tree prefix sharing over the pool (engine/radix_cache.py):
    # concurrent users share the system prompt's blocks copy-on-write,
    # multi-turn /execute loops re-map their whole history instead of
    # re-prefilling it. false = pool without sharing (A/B).
    radix_cache: bool = True                # RADIX_CACHE
    # LRU budget (blocks) the radix tree may keep cached. 0 = auto
    # (a quarter of the pool).
    radix_lru_blocks: int = 0               # RADIX_LRU_BLOCKS
    # --- two-tier KV: host-RAM block offload (ISSUE 20) ---
    # Capacity (blocks) of the pinned host-RAM second tier behind the
    # radix tree: eviction under HBM pressure DEMOTES cold chains there
    # (CRC32-stamped) instead of discarding them, and a returning
    # session's match transparently onloads them back — checksum
    # verified, falling back to ordinary suffix prefill on any failure.
    # 0 disables the tier (eviction discards, the single-tier world).
    host_kv_blocks: int = 0                 # HOST_KV_BLOCKS
    # --- grammar-constrained decoding (ISSUE 11; constrain/) ---
    # Compile the kubectl grammar against the tokenizer into a token
    # FSM, mask logits device-side so only grammar-legal tokens can be
    # sampled (unsafe commands become unrepresentable, not merely
    # rejected), and fast-forward forced runs (single-successor chains)
    # as one suffix prefill instead of decoding token-by-token.
    # Requires DEVICE_TERMINATION (the FSM state word rides the decode
    # chunk's carry). Default off: A/B parity with unconstrained decode
    # is the acceptance gate.
    grammar_decode: bool = False            # GRAMMAR_DECODE
    # Base grammar profile: "default" (read-only + mutating verbs),
    # "readonly" (observation only — also what a background-tier tenant
    # is clamped to per request), or "permissive" (mask-everything A/B:
    # grammar plumbing active, language unconstrained).
    grammar_profile: str = "default"        # GRAMMAR_PROFILE
    # Minimum NET forced-run length worth a fast-forward splice: the
    # scheduler only splices when the forced chain exceeds what the
    # in-flight speculative chunks would decode anyway (their compute
    # is sunk; discarding them must buy more than it costs).
    grammar_forced_run_min: int = 4         # GRAMMAR_FORCED_RUN_MIN
    # --- speculative decoding (ISSUE 12; engine/batcher.py) ---
    # Run a small draft model (the 2B) that proposes SPEC_DRAFT_K tokens
    # per slot per verify step; ONE 7B forward over the k+1-token window
    # then verifies them all — more transcript tokens per 7B weight
    # read, the remaining single-chip lever once decode is pinned at the
    # int8 weight-read floor. Verification is exact-match against the
    # 7B's own seeded sample, so transcripts are byte-identical to
    # SPEC_DECODE=false at any k (the acceptance gate). Requires
    # DEVICE_TERMINATION (the accept/reject fold rides the chunk carry)
    # and the KV pool (dense/mesh layouts fall back to plain decode).
    spec_decode: bool = False               # SPEC_DECODE
    # Draft tokens proposed per verify step (>= 1). Throughput =
    # accepted-rate-dependent; greedy kubectl outputs accept at very
    # high rates, and acceptance is a first-class /metrics signal
    # (spec_acceptance_ratio).
    spec_draft_k: int = 4                   # SPEC_DRAFT_K
    # Draft model registry name; must share the target's tokenizer /
    # vocab (validated at boot).
    spec_draft_model: str = "gemma-2b-it"   # SPEC_DRAFT_MODEL
    # Draft checkpoint dir (unset = random init, toy/dev mode only).
    spec_draft_path: Optional[str] = None   # SPEC_DRAFT_PATH
    hbm_prefix_cache: bool = True           # HBM_PREFIX_CACHE (system-prompt prefix KV)
    # Scheduler watchdog: if the batch scheduler makes no progress for this
    # long while work is in flight (hung device dispatch), the engine is
    # marked degraded and every waiting request is failed. 0 disables.
    engine_watchdog_secs: float = 120.0     # ENGINE_WATCHDOG_SECS
    # Cold-start grace for the watchdog: until the scheduler has consumed
    # its first decode-pipeline entry — and while an admission (the
    # lazy-compile site) is mid-flight — no-progress is judged against
    # max(ENGINE_WATCHDOG_SECS, this), so a >2-minute cold 7B compile is
    # not mis-read as a hung dispatch that degrades the engine and fails
    # waiting slots. Steady-state hangs still trip at the watchdog value.
    engine_startup_grace_secs: float = 900.0  # ENGINE_STARTUP_GRACE_SECS
    # HBM budget (MB) for batched-admission scratch KV: group sizes whose
    # kpad × suffix-depth scratch rows exceed it are dropped per shape
    # (groups split smaller / fall back to singles). Bounds the admission
    # transient that, with the old full-depth scratch, kept bs=64 from
    # fitting beside 7B int8 weights. 0 = uncapped.
    admit_scratch_mb: int = 512             # ADMIT_SCRATCH_MB

    # --- engine fleet (engine/fleet.py; ROADMAP item 5's router step) ---
    # Replicated engines behind one facade: N engine replicas with
    # health-aware routing, cross-replica migration (seeded replay makes
    # a migrated request's transcript bit-identical), zero-downtime
    # drains, and hedged re-dispatch. 1 = no fleet layer (the default:
    # single engine, zero overhead).
    fleet_size: int = 1                     # FLEET_SIZE
    # Hedged re-dispatch: if the chosen replica produces no event within
    # this budget, the same request (same seed — identical bytes) is
    # raced on a second replica. 0 disables.
    fleet_hedge_ms: float = 0.0             # FLEET_HEDGE_MS
    # Prefix-affinity routing: keep multi-turn /execute agent loops on
    # the replica already holding their KV prefix.
    fleet_affinity: bool = True             # FLEET_AFFINITY
    # How many times one request may migrate across replicas before its
    # error propagates (bounds pathological flapping).
    fleet_migration_budget: int = 3         # FLEET_MIGRATION_BUDGET
    # Auto-rejoin: restart an ejected replica after this many seconds
    # (each rejoin needs a successful engine start). 0 = manual rejoin
    # only (drain/eject leaves the replica down until an operator acts).
    fleet_rejoin_secs: float = 0.0          # FLEET_REJOIN_SECS

    # --- zero-downtime weight rollout (ISSUE 13; engine/rollout.py) ---
    # Fraction of FRESH traffic the router steers at the canary replica
    # while a rollout observes it. Clamped to (0, 0.5] at boot — the
    # canary must never be able to starve the stable cohort's
    # interactive lane.
    rollout_canary_share: float = 0.1       # ROLLOUT_CANARY_SHARE
    # How long the canary serves its bounded share before the promotion
    # gate's verdict: canary-vs-stable on SLO burn (fast window),
    # goodput ratio, quarantine/grammar-dead-end counters, breaker.
    rollout_observe_secs: float = 60.0      # ROLLOUT_OBSERVE_SECS
    # Burn-gate factor: the canary rolls back when its fast-window burn
    # reaches this multiple of max(1.0, the stable cohort's burn) — a
    # fleet already burning from ambient load must not auto-roll a
    # canary back for matching it. >= 1.
    rollout_burn_gate: float = 2.0          # ROLLOUT_BURN_GATE

    # --- QoS ring (ISSUE 7; engine/qos.py) ---
    # Tenant tiers: "tenantKey:lane,..." mapping a tenant key (the API
    # key a client presents, else its client IP) to the HIGHEST lane it
    # may claim (interactive | batch | background). An X-Priority header
    # can lower a request below its tier but never raise it above.
    # Unlisted tenants default to QOS_DEFAULT_LANE.
    tenant_tiers: str = ""                  # TENANT_TIERS
    # Lane a request runs in when neither TENANT_TIERS nor X-Priority
    # names one. "interactive" keeps single-tenant deployments exactly
    # as fast as before the QoS ring existed.
    qos_default_lane: str = "interactive"   # QOS_DEFAULT_LANE
    # WDRR lane weights: one saturated scheduling round serves this many
    # requests per lane ("interactive:8,batch:4,background:1").
    lane_weights: str = ""                  # LANE_WEIGHTS
    # Per-tenant in-queue cap: a tenant with this many requests already
    # waiting is shed with a fast 429 (the flooding tenant's problem,
    # not everyone's 503). 0 = no cap below MAX_QUEUE_DEPTH.
    tenant_max_queue: int = 0               # TENANT_MAX_QUEUE
    # Per-session token budget (ISSUE 20): once a session (X-Session-ID
    # header) has been delivered this many completion tokens, its later
    # requests classify into the background lane — the session keeps
    # working, it just stops outranking fresh interactive traffic.
    # Graceful by design: never a reject. 0 disables budgets.
    qos_session_token_budget: int = 0       # QOS_SESSION_TOKEN_BUDGET
    # Preemptive decode: once a higher-lane request has queue-waited
    # this long with every slot busy, the scheduler exports the
    # cheapest lower-lane victim (PR 6 RequestExport path), frees its
    # slot, and re-enqueues it at the head of its tenant queue for a
    # bit-identical seeded replay. 0 disables preemption.
    preempt_wait_ms: float = 500.0          # PREEMPT_WAIT_MS
    # How many times one request may be preempted before it becomes
    # un-preemptable (victim selection skips it) — bounds livelock.
    preempt_budget: int = 2                 # PREEMPT_BUDGET
    # Interactive queue-wait SLO driving the AIMD brownout controller:
    # when interactive p95 queue wait breaches this, background's slot
    # share halves first (then batch); recovery is additive, batch
    # first. 0 disables the controller.
    slo_interactive_ms: float = 2000.0      # SLO_INTERACTIVE_MS

    # --- overload protection / failure containment ---
    # Bounded admission: the batcher sheds work with a fast 503 +
    # Retry-After once this many requests are queued for a decode slot,
    # instead of queueing doomed work until it 504s at llm_timeout.
    # 0 = unbounded (the pre-containment behaviour). Enforced by the
    # continuous-batching engine (the default); single-sequence jax /
    # fake / openai deployments rely on MAX_INFLIGHT_REQUESTS instead.
    max_queue_depth: int = 64               # MAX_QUEUE_DEPTH
    # HTTP-layer cap on concurrently-processing generation requests
    # (/kubectl-command + /kubectl-command/stream); excess sheds with a
    # fast 503 + Retry-After before touching the engine. 0 = unlimited.
    max_inflight_requests: int = 256        # MAX_INFLIGHT_REQUESTS
    # Serve rule-based FallbackEngine responses (degraded: true, HTTP 200)
    # instead of 503 while the circuit breaker is open / the engine fails.
    degraded_fallback: bool = False         # DEGRADED_FALLBACK
    # Circuit breaker around the engine: opens after this many engine
    # failures within breaker_window_secs (0 disables); after
    # breaker_recovery_secs one half-open probe re-closes it on success.
    breaker_threshold: int = 5              # BREAKER_THRESHOLD
    breaker_window_secs: float = 30.0       # BREAKER_WINDOW_SECS
    breaker_recovery_secs: float = 15.0     # BREAKER_RECOVERY_SECS
    # --- blast-radius containment (the INNER ring; engine/containment.py)
    # Device-side per-slot health detection in the decode chunk: NaN/Inf
    # logits and out-of-range sampled token ids trip a health word in the
    # packed chunk buffer, freezing the slot mid-chunk and feeding the
    # quarantine pass. false drops detection (step-exception containment
    # stays).
    slot_health_check: bool = True          # SLOT_HEALTH_CHECK
    # How many times one request may be solo-implicated in a poisoned
    # step (health bit, or isolated by bisection) and still be replayed;
    # past this it fails terminally with HTTP 410. 0 = quarantine on
    # first trip.
    quarantine_retry_budget: int = 1        # QUARANTINE_RETRY_BUDGET
    # Engine reset-and-replay rate limit (per rolling minute): past it
    # the engine stops resetting and fails the affected requests fast —
    # the errors feed the circuit breaker, which is the outer ring's
    # job. 0 = unlimited.
    engine_reset_max_per_min: int = 12      # ENGINE_RESET_MAX_PER_MIN
    # Fault-injection harness (testing/faults.py):
    # "admit:error:0.5,chunk:hang,generate:delay:2.0" — plus the
    # containment drills "decode:nan:<p>", "decode:poison_step",
    # "scheduler:die". Empty disables.
    fault_points: str = ""                  # FAULT_POINTS

    # --- observability ---
    # Flight recorder: keep the full span timeline of the last N requests
    # (including shed/degraded/errored) for /debug/requests lookups.
    flight_recorder_size: int = 256         # FLIGHT_RECORDER_SIZE
    # Goodput ledger (obs/ledger.py): classify every device decode step
    # delivered | replayed | preempted | hedge_loser | wasted_masked |
    # quarantine_burn, per lane (metrics) and per hashed tenant
    # (/debug/ledger only). false disables the accounting (the waste
    # counters it mirrors keep working).
    ledger_enable: bool = True              # LEDGER_ENABLE
    # TTFT SLO target (ms) for the burn-rate engine (obs/slo.py): a
    # finished request whose first token took longer than this breaches.
    # 0 disables the TTFT slo (queue-wait burn still runs off
    # SLO_INTERACTIVE_MS).
    slo_ttft_ms: float = 5000.0             # SLO_TTFT_MS
    # Turn-N TTFT SLO for returning sessions (ISSUE 20): judged ONLY
    # for radix-warm re-admissions (the match covered at least one full
    # page), so it prices exactly what the two-tier KV cache exists for
    # — a warm agent turn must start streaming this fast. 0 disables.
    slo_session_ttft_ms: float = 0.0        # SLO_SESSION_TTFT_MS
    # Burn-rate windows (seconds, ascending, at most 4 — each is a
    # metric label value): the classic fast/slow multi-window pair.
    slo_windows: str = "300,3600"           # SLO_WINDOWS
    # Success-rate objective the error budget is priced from: at 0.99,
    # 1% of samples may breach before burn rate 1.0.
    slo_objective: float = 0.99             # SLO_OBJECTIVE
    # --- perf-regression sentinel (ISSUE 15; obs/steptime.py) ---
    # Baseline envelope file for the step-time sentinel: JSON with a
    # step_time_ms table ({phase: {bucket|"default": ms}}), seeded from
    # the BENCH_r*.json numbers of record (PERF_BASELINES.json in the
    # repo root). Empty = no file; every digest then self-calibrates
    # from its first SENTINEL_MIN_SAMPLES samples. A set-but-unloadable
    # path refuses to boot.
    perf_baselines: str = ""                # PERF_BASELINES
    # Master switch for the always-on step-time digests + breach
    # detection (the digests are a bounded ring per (phase, bucket) —
    # the cost of leaving this on is one deque append per chunk cycle).
    sentinel_enable: bool = True            # SENTINEL_ENABLE
    # Samples kept per (phase, bucket) digest (the p50/p95/p99 window).
    sentinel_window: int = 256              # SENTINEL_WINDOW
    # Breach rule: recent p99 > factor x baseline trips the sentinel.
    sentinel_factor: float = 2.0            # SENTINEL_FACTOR
    # Samples required before a digest may breach (also the
    # self-calibration window when no file baseline covers the key).
    sentinel_min_samples: int = 16          # SENTINEL_MIN_SAMPLES
    # Incident-watcher evaluation period (seconds): a background task
    # polls the cheap health views for firing triggers this often.
    # 0 = no background watcher (triggers still evaluate at /metrics
    # scrapes and /debug/incidents reads).
    sentinel_eval_secs: float = 2.0         # SENTINEL_EVAL_SECS
    # --- incident capture (ISSUE 15; obs/incidents.py) ---
    # How many incident bundles the /debug/incidents ring retains.
    incident_ring: int = 8                  # INCIDENT_RING
    # Per-trigger cooldown: within it further firings of the same
    # trigger are counted suppressed but assemble NOTHING — capture
    # overhead can never cascade during the incident it is observing.
    incident_cooldown_secs: float = 60.0    # INCIDENT_COOLDOWN_SECS
    # Fast-window SLO burn at or above this fires the slo_fast_burn
    # trigger. 0 disables the burn trigger.
    incident_burn_threshold: float = 2.0    # INCIDENT_BURN_THRESHOLD
    # Attach a rate-limited jax.profiler capture of this many seconds
    # to each new bundle (jax engines only). 0 = off (the default —
    # captures are tens of MB and cost real device time).
    incident_profile_secs: float = 0.0      # INCIDENT_PROFILE_SECS
    # host_tier_thrash trigger sensitivity (ISSUE 20): both the demote
    # AND onload deltas since the last evaluation must reach this many
    # blocks to file a churn incident (one-way flow is warmup/drain,
    # not thrash). 0 disables the trigger.
    incident_thrash_min_blocks: int = 8     # INCIDENT_THRASH_MIN_BLOCKS
    # Optional canary-vs-stable step-time verdict in the weight-rollout
    # promotion gate: the canary rolls back when its decode p95 reaches
    # this multiple of the stable cohort's. 0 = off; >= 1 otherwise.
    rollout_steptime_gate: float = 0.0      # ROLLOUT_STEPTIME_GATE
    # Debug-endpoint token: when set, /debug/* additionally requires
    # X-Debug-Token (profiler captures and request timelines are
    # operator-facing, not client-facing). Unset = only API-key auth
    # (when enabled) guards them.
    debug_token: Optional[str] = None       # DEBUG_TOKEN
    # Graceful shutdown: stop accepting new requests, wait up to this long
    # for in-flight generations to finish, then abort what remains.
    drain_timeout_secs: float = 10.0        # DRAIN_TIMEOUT_SECS
    # Persistent XLA compilation cache: warm restarts skip the multi-second
    # per-program compiles (engine startup drops from ~80s to seconds).
    # Empty string disables.
    compile_cache_dir: str = "~/.cache/ai-agent-kubectl-tpu/xla-cache"  # COMPILE_CACHE_DIR

    # --- parallelism knobs ---
    mesh_shape: str = ""                    # MESH_SHAPE e.g. "data:1,model:8"
    dcn_mesh_shape: str = ""                # DCN_MESH_SHAPE for multi-slice
    distributed_init: bool = False          # DISTRIBUTED_INIT (jax.distributed.initialize)
    coordinator_address: Optional[str] = None   # COORDINATOR_ADDRESS
    num_processes: int = 1                  # NUM_PROCESSES
    process_id: int = 0                     # PROCESS_ID

    # --- openai-compat engine (reference parity path, app.py:34-36) ---
    openai_api_key: Optional[str] = None    # OPENAI_API_KEY
    openai_model: str = "gpt-3.5-turbo"     # OPENAI_MODEL
    openai_base_url: Optional[str] = None   # OPENAI_BASE_URL

    # derived
    rate_limit_count: int = field(init=False, default=10)
    rate_limit_window: float = field(init=False, default=60.0)

    def __post_init__(self):
        count, window = parse_rate_limit(self.rate_limit)
        object.__setattr__(self, "rate_limit_count", count)
        object.__setattr__(self, "rate_limit_window", window)
        # Validate the QoS specs at boot — a typo'd tier or weight must
        # refuse to start, not silently skew the scheduler. (Lazy import:
        # config is the base layer; engine.qos only pulls stdlib +
        # engine.protocol.)
        self.tenant_tier_map
        self.lane_weight_map
        # SLO knobs (ISSUE 8): a typo'd window list or an objective
        # outside (0,1) must refuse to boot, not serve meaningless burn
        # rates.
        self.slo_window_list
        if not 0.0 < self.slo_objective < 1.0:
            raise ValueError(
                f"SLO_OBJECTIVE must be in (0, 1), got {self.slo_objective}")
        if self.slo_ttft_ms < 0:
            raise ValueError(
                f"SLO_TTFT_MS must be >= 0, got {self.slo_ttft_ms}")
        # Perf-regression sentinel + incident knobs (ISSUE 15): a
        # typo'd factor/window or an unloadable baselines file must
        # refuse to boot, not silently disarm the regression trigger.
        if self.sentinel_window < 8:
            raise ValueError(
                f"SENTINEL_WINDOW must be >= 8 samples, "
                f"got {self.sentinel_window}")
        if self.sentinel_factor < 1.0:
            raise ValueError(
                f"SENTINEL_FACTOR must be >= 1 (a factor below 1 would "
                f"trip on every healthy step), got {self.sentinel_factor}")
        if self.sentinel_min_samples < 1:
            raise ValueError(
                f"SENTINEL_MIN_SAMPLES must be >= 1, "
                f"got {self.sentinel_min_samples}")
        if self.sentinel_eval_secs < 0:
            raise ValueError(
                f"SENTINEL_EVAL_SECS must be >= 0 (0 = scrape-driven "
                f"only), got {self.sentinel_eval_secs}")
        if self.incident_ring < 1:
            raise ValueError(
                f"INCIDENT_RING must be >= 1, got {self.incident_ring}")
        if self.incident_cooldown_secs < 0:
            raise ValueError(
                f"INCIDENT_COOLDOWN_SECS must be >= 0, "
                f"got {self.incident_cooldown_secs}")
        if self.incident_burn_threshold < 0:
            raise ValueError(
                f"INCIDENT_BURN_THRESHOLD must be >= 0 (0 disables), "
                f"got {self.incident_burn_threshold}")
        if not 0.0 <= self.incident_profile_secs <= 30.0:
            raise ValueError(
                f"INCIDENT_PROFILE_SECS must be in [0, 30] (captures "
                f"are tens of MB each), got {self.incident_profile_secs}")
        if self.rollout_steptime_gate != 0.0 \
                and self.rollout_steptime_gate < 1.0:
            raise ValueError(
                f"ROLLOUT_STEPTIME_GATE must be 0 (off) or >= 1 (a "
                f"factor below 1 would roll back every healthy canary), "
                f"got {self.rollout_steptime_gate}")
        if self.perf_baselines:
            from .obs.steptime import load_baselines

            try:
                load_baselines(self.perf_baselines)
            except (OSError, ValueError, KeyError) as e:
                raise ValueError(
                    f"PERF_BASELINES {self.perf_baselines!r} failed to "
                    f"load: {e}") from e
        # KV pool knobs (ISSUE 10): the page must divide the 128-token
        # kv-limit tile (kv buckets are 128-tiled, so every attention
        # gather width must be a whole page count) and the prefill-chunk
        # alignment rides the same tile. A bad page must refuse to boot,
        # not mis-index the pool.
        if self.kv_pool_page < 1 or 128 % self.kv_pool_page:
            raise ValueError(
                f"KV_POOL_PAGE must divide the 128-token chunk/kv-limit "
                f"tile (8|16|32|64|128), got {self.kv_pool_page}")
        if self.kv_pool_blocks < 0:
            raise ValueError(
                f"KV_POOL_BLOCKS must be >= 0 (0 = auto), "
                f"got {self.kv_pool_blocks}")
        if self.radix_lru_blocks < 0:
            raise ValueError(
                f"RADIX_LRU_BLOCKS must be >= 0 (0 = auto), "
                f"got {self.radix_lru_blocks}")
        # Two-tier KV + session knobs (ISSUE 20): negative capacities
        # and budgets must refuse to boot, and the host tier only means
        # something over the block pool + radix tree it demotes from.
        if self.host_kv_blocks < 0:
            raise ValueError(
                f"HOST_KV_BLOCKS must be >= 0 (0 disables the host "
                f"tier), got {self.host_kv_blocks}")
        if self.host_kv_blocks > 0 and not (self.kv_pool
                                            and self.radix_cache):
            raise ValueError(
                "HOST_KV_BLOCKS requires KV_POOL=true and "
                "RADIX_CACHE=true (the host tier is the radix tree's "
                "demotion target — without the tree there is nothing "
                "to demote)")
        if self.slo_session_ttft_ms < 0:
            raise ValueError(
                f"SLO_SESSION_TTFT_MS must be >= 0 (0 disables), "
                f"got {self.slo_session_ttft_ms}")
        if self.qos_session_token_budget < 0:
            raise ValueError(
                f"QOS_SESSION_TOKEN_BUDGET must be >= 0 (0 disables), "
                f"got {self.qos_session_token_budget}")
        if self.incident_thrash_min_blocks < 0:
            raise ValueError(
                f"INCIDENT_THRASH_MIN_BLOCKS must be >= 0 (0 disables), "
                f"got {self.incident_thrash_min_blocks}")
        # Ragged attention knob (ISSUE 19): a typo'd mode must refuse
        # to boot, not silently serve the legacy ladder behind a knob
        # that says otherwise. "on" additionally needs the pool (ragged
        # is a kernel OVER the block pool — there is no dense variant).
        if self.ragged_attention not in ("auto", "on", "off"):
            raise ValueError(
                f"RAGGED_ATTENTION must be auto|on|off, "
                f"got {self.ragged_attention!r}")
        if self.ragged_attention == "on" and not self.kv_pool:
            raise ValueError(
                "RAGGED_ATTENTION=on requires KV_POOL=true (the ragged "
                "kernel reads per-slot block tables over the shared "
                "pool — the dense ladder has no ragged variant)")
        # Grammar knobs (ISSUE 11): a typo'd profile or an impossible
        # mode combination must refuse to boot, not silently serve
        # unconstrained output behind a knob that says otherwise.
        from .constrain.runtime import PROFILES

        if self.grammar_profile not in PROFILES:
            raise ValueError(
                f"GRAMMAR_PROFILE must be one of {PROFILES}, "
                f"got {self.grammar_profile!r}")
        if self.grammar_forced_run_min < 1:
            raise ValueError(
                f"GRAMMAR_FORCED_RUN_MIN must be >= 1, "
                f"got {self.grammar_forced_run_min}")
        if self.grammar_decode and not self.device_termination:
            raise ValueError(
                "GRAMMAR_DECODE requires DEVICE_TERMINATION=true (the "
                "FSM state word rides the decode chunk's carry)")
        if self.grammar_decode:
            # Boot-time cross-check (defense-in-depth satellite): every
            # safety-blocked verb must be absent from every profile.
            from .constrain import assert_safety_consistent

            assert_safety_consistent()
        # Weight-rollout knobs (ISSUE 13): a canary share outside
        # (0, 0.5] either disables the observe phase silently or lets
        # the canary starve the stable cohort — both refuse to boot.
        if not 0.0 < self.rollout_canary_share <= 0.5:
            raise ValueError(
                f"ROLLOUT_CANARY_SHARE must be in (0, 0.5] (the canary "
                f"may never take more fresh traffic than the stable "
                f"cohort), got {self.rollout_canary_share}")
        if self.rollout_observe_secs < 0:
            raise ValueError(
                f"ROLLOUT_OBSERVE_SECS must be >= 0, "
                f"got {self.rollout_observe_secs}")
        if self.rollout_burn_gate < 1.0:
            raise ValueError(
                f"ROLLOUT_BURN_GATE must be >= 1 (a factor below the "
                f"sustainable burn rate would roll back every healthy "
                f"canary), got {self.rollout_burn_gate}")
        # Speculative-decode knobs (ISSUE 12): an impossible combination
        # or an unknown/mismatched draft model must refuse to boot, not
        # silently serve plain decode behind a knob that says otherwise.
        if self.spec_decode:
            if not self.device_termination:
                raise ValueError(
                    "SPEC_DECODE requires DEVICE_TERMINATION=true (the "
                    "accept/reject fold rides the decode chunk's carry)")
            if self.spec_draft_k < 1:
                raise ValueError(
                    f"SPEC_DRAFT_K must be >= 1, got {self.spec_draft_k}")
            from .models.config import get_config as _get_model_config

            try:
                draft = _get_model_config(self.spec_draft_model)
            except KeyError:
                raise ValueError(
                    f"SPEC_DRAFT_MODEL {self.spec_draft_model!r} is not "
                    f"a known model registry name") from None
            try:
                target = _get_model_config(self.model_name)
            except KeyError:
                target = None   # MODEL_NAME errors are the engine's job
            if (target is not None
                    and draft.vocab_size != target.vocab_size):
                raise ValueError(
                    f"SPEC_DRAFT_MODEL {self.spec_draft_model!r} "
                    f"(vocab {draft.vocab_size}) does not share "
                    f"{self.model_name!r}'s vocab ({target.vocab_size}) "
                    f"— draft and verifier must use one tokenizer")
            # ISSUE 18: the draft world is mesh-native under tp/ep —
            # draft cache/params shard per parallel/sharding.py's
            # draft_cache_specs and the spec chunk compiles against the
            # mesh — so SPEC_DECODE + MESH_SHAPE now composes. What
            # remains genuinely unshardable is the spec pool's
            # requirement (blocks never shard over data/pipe/seq) plus
            # the draft's whole-stack ride of the mesh: refuse only a
            # >1 data/pipe/seq axis (the engine re-checks at start for
            # direct construction; the capability check stays jax-free).
            bad = sorted(
                _mesh_unshardable_axes(self.mesh_shape)
                | _mesh_unshardable_axes(self.dcn_mesh_shape))
            if bad:
                raise ValueError(
                    f"SPEC_DECODE does not compose with a mesh that has "
                    f"a >1 {'/'.join(bad)} axis (MESH_SHAPE="
                    f"{self.mesh_shape!r} DCN_MESH_SHAPE="
                    f"{self.dcn_mesh_shape!r}): the spec KV pool's "
                    f"blocks and the draft verify window shard over "
                    f"tp/ep only — use a tensor/expert-parallel mesh or "
                    f"disable one of them")

    @property
    def tenant_tier_map(self) -> dict:
        from .engine.qos import LANES, parse_tenant_tiers

        if self.qos_default_lane not in LANES:
            raise ValueError(
                f"QOS_DEFAULT_LANE must be one of {LANES}, "
                f"got {self.qos_default_lane!r}")
        return parse_tenant_tiers(self.tenant_tiers)

    @property
    def lane_weight_map(self) -> dict:
        from .engine.qos import parse_lane_weights

        return parse_lane_weights(self.lane_weights)

    @property
    def slo_window_list(self) -> Tuple[int, ...]:
        from .obs.slo import parse_slo_windows

        return parse_slo_windows(self.slo_windows)

    @property
    def auth_enabled(self) -> bool:
        return bool(self.api_auth_key)

    @property
    def prefill_bucket_list(self) -> Tuple[int, ...]:
        return tuple(sorted(int(b) for b in self.prefill_buckets.split(",") if b.strip()))

    @classmethod
    def from_env(cls, env_file: str | os.PathLike | None = ".env") -> "ServiceConfig":
        if env_file is not None:
            load_env_file(env_file)
        return cls(
            api_auth_key=_env_str("API_AUTH_KEY", None),
            cache_maxsize=_env_int("CACHE_MAXSIZE", 100),
            cache_ttl=_env_float("CACHE_TTL", 300.0),
            llm_timeout=_env_float("LLM_TIMEOUT", 60.0),
            execution_timeout=_env_float("EXECUTION_TIMEOUT", 30.0),
            rate_limit=_env_str("RATE_LIMIT", "10/minute"),
            log_level=(_env_str("LOG_LEVEL", "INFO") or "INFO").upper(),
            log_format=(_env_str("LOG_FORMAT", "text") or "text").lower(),
            host=_env_str("HOST", "0.0.0.0"),
            port=_env_int("PORT", 8000),
            # TRUST_PROXY is the conventional short alias (fronting
            # router tiers set it); TRUST_PROXY_HEADERS wins when both
            # are present.
            trust_proxy_headers=_env_bool(
                "TRUST_PROXY_HEADERS", _env_bool("TRUST_PROXY", False)),
            engine=(_env_str("ENGINE", "jax") or "jax").lower(),
            model_name=_env_str("MODEL_NAME", "toy-8m"),
            model_path=_env_str("MODEL_PATH", None),
            tokenizer_path=_env_str("TOKENIZER_PATH", None),
            dtype=_env_str("DTYPE", "bfloat16"),
            quant=(_env_str("QUANT", "") or "").lower(),
            kv_quant=(_env_str("KV_QUANT", "") or "").lower(),
            max_seq_len=_env_int("MAX_SEQ_LEN", 1024),
            max_new_tokens=_env_int("MAX_NEW_TOKENS", 128),
            decode_batch_size=_env_int("DECODE_BATCH_SIZE", 8),
            chunk_len=_env_int("CHUNK_LEN", 16),
            chunk_pipe_depth=_env_int("CHUNK_PIPE_DEPTH", 3),
            device_termination=_env_bool("DEVICE_TERMINATION", True),
            prefill_buckets=_env_str("PREFILL_BUCKETS", "64,128,256,512,1024"),
            temperature=_env_float("TEMPERATURE", 0.0),
            top_k=_env_int("TOP_K", 0),
            top_p=_env_float("TOP_P", 1.0),
            attn_impl=(_env_str("ATTN_IMPL", "auto") or "auto").lower(),
            decode_attn=(_env_str("DECODE_ATTN", "auto") or "auto").lower(),
            moe_impl=(_env_str("MOE_IMPL", "auto") or "auto").lower(),
            kv_page_size=_env_int("KV_PAGE_SIZE", 16),
            ragged_attention=(_env_str("RAGGED_ATTENTION", "auto")
                              or "auto").lower(),
            kv_pool=_env_bool("KV_POOL", True),
            kv_pool_page=_env_int("KV_POOL_PAGE", 16),
            kv_pool_blocks=_env_int("KV_POOL_BLOCKS", 0),
            radix_cache=_env_bool("RADIX_CACHE", True),
            radix_lru_blocks=_env_int("RADIX_LRU_BLOCKS", 0),
            host_kv_blocks=_env_int("HOST_KV_BLOCKS", 0),
            grammar_decode=_env_bool("GRAMMAR_DECODE", False),
            grammar_profile=(_env_str("GRAMMAR_PROFILE", "default")
                             or "default").lower(),
            grammar_forced_run_min=_env_int("GRAMMAR_FORCED_RUN_MIN", 4),
            spec_decode=_env_bool("SPEC_DECODE", False),
            spec_draft_k=_env_int("SPEC_DRAFT_K", 4),
            spec_draft_model=_env_str("SPEC_DRAFT_MODEL", "gemma-2b-it"),
            spec_draft_path=_env_str("SPEC_DRAFT_PATH", None),
            hbm_prefix_cache=_env_bool("HBM_PREFIX_CACHE", True),
            engine_watchdog_secs=_env_float("ENGINE_WATCHDOG_SECS", 120.0),
            engine_startup_grace_secs=_env_float(
                "ENGINE_STARTUP_GRACE_SECS", 900.0),
            admit_scratch_mb=_env_int("ADMIT_SCRATCH_MB", 512),
            fleet_size=_env_int("FLEET_SIZE", 1),
            fleet_hedge_ms=_env_float("FLEET_HEDGE_MS", 0.0),
            fleet_affinity=_env_bool("FLEET_AFFINITY", True),
            fleet_migration_budget=_env_int("FLEET_MIGRATION_BUDGET", 3),
            fleet_rejoin_secs=_env_float("FLEET_REJOIN_SECS", 0.0),
            rollout_canary_share=_env_float("ROLLOUT_CANARY_SHARE", 0.1),
            rollout_observe_secs=_env_float("ROLLOUT_OBSERVE_SECS", 60.0),
            rollout_burn_gate=_env_float("ROLLOUT_BURN_GATE", 2.0),
            tenant_tiers=_env_str("TENANT_TIERS", "") or "",
            qos_default_lane=(
                _env_str("QOS_DEFAULT_LANE", "interactive")
                or "interactive").lower(),
            lane_weights=_env_str("LANE_WEIGHTS", "") or "",
            tenant_max_queue=_env_int("TENANT_MAX_QUEUE", 0),
            qos_session_token_budget=_env_int(
                "QOS_SESSION_TOKEN_BUDGET", 0),
            preempt_wait_ms=_env_float("PREEMPT_WAIT_MS", 500.0),
            preempt_budget=_env_int("PREEMPT_BUDGET", 2),
            slo_interactive_ms=_env_float("SLO_INTERACTIVE_MS", 2000.0),
            max_queue_depth=_env_int("MAX_QUEUE_DEPTH", 64),
            max_inflight_requests=_env_int("MAX_INFLIGHT_REQUESTS", 256),
            degraded_fallback=_env_bool("DEGRADED_FALLBACK", False),
            breaker_threshold=_env_int("BREAKER_THRESHOLD", 5),
            breaker_window_secs=_env_float("BREAKER_WINDOW_SECS", 30.0),
            breaker_recovery_secs=_env_float("BREAKER_RECOVERY_SECS", 15.0),
            slot_health_check=_env_bool("SLOT_HEALTH_CHECK", True),
            quarantine_retry_budget=_env_int("QUARANTINE_RETRY_BUDGET", 1),
            engine_reset_max_per_min=_env_int("ENGINE_RESET_MAX_PER_MIN", 12),
            fault_points=_env_str("FAULT_POINTS", "") or "",
            flight_recorder_size=_env_int("FLIGHT_RECORDER_SIZE", 256),
            ledger_enable=_env_bool("LEDGER_ENABLE", True),
            slo_ttft_ms=_env_float("SLO_TTFT_MS", 5000.0),
            slo_session_ttft_ms=_env_float("SLO_SESSION_TTFT_MS", 0.0),
            slo_windows=_env_str("SLO_WINDOWS", "300,3600") or "300,3600",
            slo_objective=_env_float("SLO_OBJECTIVE", 0.99),
            perf_baselines=_env_str("PERF_BASELINES", "") or "",
            sentinel_enable=_env_bool("SENTINEL_ENABLE", True),
            sentinel_window=_env_int("SENTINEL_WINDOW", 256),
            sentinel_factor=_env_float("SENTINEL_FACTOR", 2.0),
            sentinel_min_samples=_env_int("SENTINEL_MIN_SAMPLES", 16),
            sentinel_eval_secs=_env_float("SENTINEL_EVAL_SECS", 2.0),
            incident_ring=_env_int("INCIDENT_RING", 8),
            incident_cooldown_secs=_env_float(
                "INCIDENT_COOLDOWN_SECS", 60.0),
            incident_burn_threshold=_env_float(
                "INCIDENT_BURN_THRESHOLD", 2.0),
            incident_profile_secs=_env_float(
                "INCIDENT_PROFILE_SECS", 0.0),
            incident_thrash_min_blocks=_env_int(
                "INCIDENT_THRASH_MIN_BLOCKS", 8),
            rollout_steptime_gate=_env_float(
                "ROLLOUT_STEPTIME_GATE", 0.0),
            debug_token=_env_str("DEBUG_TOKEN", None),
            drain_timeout_secs=_env_float("DRAIN_TIMEOUT_SECS", 10.0),
            compile_cache_dir=os.getenv(
                "COMPILE_CACHE_DIR", "~/.cache/ai-agent-kubectl-tpu/xla-cache"
            ),
            mesh_shape=_env_str("MESH_SHAPE", "") or "",
            dcn_mesh_shape=_env_str("DCN_MESH_SHAPE", "") or "",
            distributed_init=_env_bool("DISTRIBUTED_INIT", False),
            coordinator_address=_env_str("COORDINATOR_ADDRESS", None),
            num_processes=_env_int("NUM_PROCESSES", 1),
            process_id=_env_int("PROCESS_ID", 0),
            openai_api_key=_env_str("OPENAI_API_KEY", None),
            openai_model=_env_str("OPENAI_MODEL", "gpt-3.5-turbo"),
            openai_base_url=_env_str("OPENAI_BASE_URL", None),
        )

    def describe(self) -> dict:
        """Loggable, secret-free view of the config."""
        d = {f.name: getattr(self, f.name) for f in fields(self) if f.init}
        for secret in ("api_auth_key", "openai_api_key", "debug_token"):
            if d.get(secret):
                d[secret] = "***"
        if d.get("tenant_tiers"):
            # Tenant keys are API keys; log only the lane assignments.
            d["tenant_tiers"] = ",".join(
                f"***:{lane}" for lane in self.tenant_tier_map.values())
        return d
