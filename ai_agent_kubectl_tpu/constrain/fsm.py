"""Token-level FSM: the byte DFA compiled against a tokenizer.

SGLang's compressed-FSM technique (PAPERS.md): the grammar is enforced
per *token*, not per byte — each decode step needs (a) the set of token
ids legal from the current state (the sampling mask) and (b) the state
the sampled token leads to. Materializing a dense ``[n_states, vocab]``
transition table on device would be ~100 MB at a 256k vocab, so the
compile collapses the token axis to *equivalence classes*: two tokens
share a class iff they induce the same state→state map (identical
columns of the dest matrix). Real grammars compress 256k tokens into a
few hundred classes, so the device carries

- ``tok_class``  [vocab]            int32 — token → class,
- ``class_next`` [n_states, n_cls]  int32 — state × class → state,
- ``class_ok``   [n_states, n_cls]  bool  — legal from this state
  (next != DEAD; the EOS class is legal exactly in accept states),

a few hundred KB total, gathered per decode step inside the jitted
chunk scan (engine/batcher.py).

Forced runs are precomputed host-side: a state with exactly ONE legal
token id starts a forced chain the scheduler can splice in a single
suffix prefill instead of decoding token-by-token (the fast-forward
tentpole). ``forced_tok[s]`` is that token id (-1 otherwise);
``forced_eos[s]`` marks accept states whose only legal token is EOS.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence

import numpy as np

from ..engine.tokenizer import ByteTokenizer, Tokenizer
from .grammar import DEAD, START, CharDFA


def token_byte_table(tokenizer: Tokenizer,
                     vocab_size: int) -> List[Optional[bytes]]:
    """UTF-8 byte string of every token id, or None for ids the FSM must
    never emit: specials (EOS is handled as its own class by the
    compiler), ids past the tokenizer's vocab (toy models over-allocate
    the embedding table), empty renderings (zero-progress tokens would
    let the FSM stall forever), and tokens whose solo decode is lossy
    (U+FFFD — byte-fallback fragments; conservative: a multi-byte
    character the grammar wants can still arrive via its whole-character
    tokens).

    For :class:`ByteTokenizer` the mapping is exact by construction.
    For HF tokenizers this is the decode-based view; left-strip
    position dependence (SentencePiece ``▁``) makes it approximate for
    leading-space pieces — acceptable for masking (conservative), noted
    here so nobody mistakes it for a round-trip guarantee.
    """
    out: List[Optional[bytes]] = [None] * vocab_size
    specials = set(getattr(tokenizer, "eos_ids", ()) or ())
    specials |= {getattr(tokenizer, "bos_id", -1),
                 getattr(tokenizer, "pad_id", -1)}
    if isinstance(tokenizer, ByteTokenizer):
        for i in range(ByteTokenizer.SPECIALS, min(vocab_size, 259)):
            out[i] = bytes([i - ByteTokenizer.SPECIALS])
        return out
    for i in range(min(vocab_size, tokenizer.vocab_size)):
        if i in specials:
            continue
        text = tokenizer.decode([i])
        if not text or "�" in text:
            continue
        out[i] = text.encode("utf-8")
    return out


@dataclasses.dataclass
class TokenFSM:
    """One compiled grammar variant (frozen numpy; device upload and
    host stepping both read these arrays)."""

    tok_class: np.ndarray     # [vocab] int32
    class_next: np.ndarray    # [n_states, n_classes] int32
    class_ok: np.ndarray      # [n_states, n_classes] bool
    accept: np.ndarray        # [n_states] bool
    forced_tok: np.ndarray    # [n_states] int32 (-1 = not forced)
    forced_eos: np.ndarray    # [n_states] bool (only-EOS accept state)
    eos_ids: tuple
    grammar_hash: str
    vocab_size: int

    @property
    def n_states(self) -> int:
        return int(self.class_next.shape[0])

    @property
    def n_classes(self) -> int:
        return int(self.class_next.shape[1])

    # ------------------------------------------------------ host stepping

    def allowed(self, state: int) -> np.ndarray:
        """[vocab] bool mask of legal token ids from ``state`` (the
        fake engine's per-step check and the admission first-token
        mask)."""
        return self.class_ok[state][self.tok_class]

    def advance(self, state: int, tok: int) -> int:
        return int(self.class_next[state, self.tok_class[tok]])

    def run(self, ids: Sequence[int], state: int = START) -> int:
        for t in ids:
            state = int(self.class_next[state, self.tok_class[t]])
            if state == DEAD:
                return DEAD
        return state

    def in_grammar(self, ids: Sequence[int]) -> bool:
        """Every step legal from START (EOS-terminated or not) — the
        test-suite oracle for "no off-grammar token was ever emitted"."""
        state = START
        for t in ids:
            if not self.class_ok[state, self.tok_class[t]]:
                return False
            state = int(self.class_next[state, self.tok_class[t]])
        return True

    def forced_run(self, state: int, cap: int) -> tuple:
        """Longest forced chain from ``state``: token ids where each
        step has exactly one legal token, capped at ``cap``. Returns
        ``(run, ends_eos, end_state)`` — ``ends_eos`` means the state
        after the run admits ONLY EOS, i.e. the command is complete and
        the scheduler can finish the request without decoding at all."""
        run: List[int] = []
        while len(run) < cap:
            if self.forced_eos[state]:
                return run, True, state
            t = int(self.forced_tok[state])
            if t < 0:
                break
            run.append(t)
            state = int(self.class_next[state, self.tok_class[t]])
        return run, bool(self.forced_eos[state]), state


def compile_token_fsm(dfa: CharDFA, tokenizer: Tokenizer,
                      vocab_size: int, eos_ids: Sequence[int],
                      _block: int = 4096) -> TokenFSM:
    """Compose the byte DFA with a tokenizer into a :class:`TokenFSM`.

    The dest matrix is computed blockwise-vectorized: token byte
    strings padded to ``[B, L]``, then L gather steps of ``[B, S]``
    through the byte-transition table — ~1k numpy ops for a 256k vocab
    instead of 1.5M Python-level walks. Columns are then interned
    (``tobytes`` keys) into equivalence classes.
    """
    S = dfa.n_states
    eos_ids = tuple(sorted(set(int(e) for e in eos_ids)))
    byte_table = token_byte_table(tokenizer, vocab_size)

    # Dead class (index 0 by convention): specials / out-of-vocab /
    # unrepresentable tokens — next == DEAD from every state.
    dead_col = np.zeros((S,), np.int32)
    classes: dict = {dead_col.tobytes(): 0}
    reps: List[np.ndarray] = [dead_col]
    tok_class = np.zeros((vocab_size,), np.int32)

    ids = [i for i, bs in enumerate(byte_table) if bs is not None]
    for lo in range(0, len(ids), _block):
        chunk = ids[lo:lo + _block]
        maxlen = max(len(byte_table[i]) for i in chunk)
        bt = np.zeros((len(chunk), maxlen), np.int64)
        ln = np.zeros((len(chunk),), np.int64)
        for j, i in enumerate(chunk):
            bs = byte_table[i]
            bt[j, :len(bs)] = np.frombuffer(bs, np.uint8)
            ln[j] = len(bs)
        cur = np.broadcast_to(np.arange(S, dtype=np.int32),
                              (len(chunk), S)).copy()
        for pos in range(maxlen):
            stepped = dfa.next[cur, bt[:, pos][:, None]]
            active = (pos < ln)[:, None]
            cur = np.where(active, stepped, cur)
        for j, i in enumerate(chunk):
            key = cur[j].tobytes()
            cls = classes.get(key)
            if cls is None:
                cls = len(reps)
                classes[key] = cls
                reps.append(cur[j].astype(np.int32))
            tok_class[i] = cls

    # EOS: its own class — next stays in place (the engine's eos_mask
    # terminates the slot; a frozen slot repeating its carry token must
    # not be able to walk the FSM into DEAD), legal exactly where the
    # char DFA accepts.
    eos_cls = len(reps)
    reps.append(np.arange(S, dtype=np.int32))
    for e in eos_ids:
        if 0 <= e < vocab_size:
            tok_class[e] = eos_cls

    C = len(reps)
    class_next = np.stack(reps, axis=1).astype(np.int32)   # [S, C]
    class_ok = class_next != DEAD
    class_ok[:, 0] = False
    class_ok[:, eos_cls] = dfa.accept
    class_next[:, 0] = DEAD
    class_ok[DEAD, :] = False
    class_next[DEAD, :] = DEAD

    # Forced chains: a state with exactly one legal TOKEN (not class —
    # a legal class holding several tokens is a choice, not a force).
    cls_size = np.bincount(tok_class, minlength=C)
    eos_only = np.zeros((S,), bool)
    forced = np.full((S,), -1, np.int32)
    for s in range(S):
        legal = np.nonzero(class_ok[s])[0]
        if legal.size != 1:
            continue     # several classes (or none) — a choice point
        cls = int(legal[0])
        if cls == eos_cls:
            eos_only[s] = True
        elif cls_size[cls] == 1:
            forced[s] = int(np.nonzero(tok_class == cls)[0][0])
    return TokenFSM(
        tok_class=tok_class,
        class_next=class_next,
        class_ok=class_ok,
        accept=dfa.accept.copy(),
        forced_tok=forced,
        forced_eos=eos_only,
        eos_ids=eos_ids,
        grammar_hash=dfa.grammar_hash,
        vocab_size=vocab_size,
    )


def compile_permissive_fsm(vocab_size: int,
                           eos_ids: Sequence[int]) -> TokenFSM:
    """The mask-everything variant ("permissive" profile): two states
    (DEAD, START), every in-vocab token legal and self-looping. The A/B
    instrument — full grammar *plumbing* (mask gathers, state word,
    forced-run checks) with the unconstrained language, so constrained
    vs unconstrained transcripts must be byte-identical."""
    eos_ids = tuple(sorted(set(int(e) for e in eos_ids)))
    tok_class = np.ones((vocab_size,), np.int32)
    class_next = np.array([[DEAD, DEAD], [DEAD, START]], np.int32)
    class_ok = np.array([[False, False], [False, True]])
    return TokenFSM(
        tok_class=tok_class,
        class_next=class_next,
        class_ok=class_ok,
        accept=np.array([False, True]),
        forced_tok=np.full((2,), -1, np.int32),
        forced_eos=np.zeros((2,), bool),
        eos_ids=eos_ids,
        grammar_hash="permissive",
        vocab_size=vocab_size,
    )
