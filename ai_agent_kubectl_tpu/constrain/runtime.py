"""Grammar runtime: compiled variants, per-request resolution, and the
stacked device tables the decode chunk gathers from.

One engine owns one :class:`GrammarRuntime`. It compiles the base
profile (``GRAMMAR_PROFILE``) and the ``readonly`` clamp target at
startup, and installs per-request *variants* (an allowed-verbs subset,
ISSUE 11) on demand into a bounded set of profile slots. All variants
are padded into ONE stacked table set —

    ``tok_class``  [P, vocab]        token → class, per profile slot
    ``class_ok``   [P·S_max, C_max]  legality, rows keyed by the
    ``class_next`` [P·S_max, C_max]  *global* state ``pid·S_max + s``

— with fixed shapes, so installing a variant updates device table
CONTENTS but never re-traces the jitted chunk program. A slot's FSM
word in the decode carry is the global state; profile identity rides
inside it (``gs // S_max``).

Per-request resolution policy (mirrors the X-Priority clamp semantics,
engine/qos.py): a request may *lower* itself to ``readonly`` (header)
and is force-clamped there when its QoS lane is ``background`` (the
TENANT_TIERS floor tier — the lowest tier must not mutate the
cluster); an allowed-verbs restriction must be a subset of the clamped
profile's verbs (validated at admission, HTTP 400 otherwise) and can
only narrow, never widen.

Thread model: ``resolve``/``install`` run on the event loop at submit
time under a lock; the scheduler thread reads the numpy tables and the
``dirty`` flag at dispatch to refresh its device copies. Table writes
happen before the flag flips, and a stale read only delays a variant
one chunk — requests carrying a pid never run before their tables are
uploaded because the pid is handed out after the install completes.
"""

from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Dict, FrozenSet, Optional, Sequence

import numpy as np

from ..engine.tokenizer import Tokenizer
from .fsm import TokenFSM, compile_permissive_fsm, compile_token_fsm
from .grammar import (DEAD, START, build_kubectl_dfa, profile_verbs)

#: named profiles an operator/request can ask for by name.
PROFILES = ("default", "readonly", "permissive")

#: headroom over the base grammar's compiled size: verb-subset variants
#: are structurally smaller, but class counts are not strictly
#: monotone, so padding carries a margin; a variant that still exceeds
#: it falls back to the clamped base profile (logged, never an error).
_STATE_MARGIN = 8
_CLASS_MARGIN = 16


@dataclasses.dataclass(frozen=True)
class GrammarContext:
    """Per-request grammar intent, carried HTTP → engine on a
    contextvar (same channel as QoSContext): the requested profile (may
    only lower) and an optional allowed-verbs narrowing."""

    profile: Optional[str] = None
    allowed_verbs: Optional[FrozenSet[str]] = None


_grammar_var: ContextVar[Optional[GrammarContext]] = ContextVar(
    "grammar_context", default=None)


def current_grammar() -> Optional[GrammarContext]:
    return _grammar_var.get()


@contextmanager
def use_grammar(ctx: GrammarContext):
    token = _grammar_var.set(ctx)
    try:
        yield ctx
    finally:
        _grammar_var.reset(token)


def clamped_profile(base: str, lane: Optional[str],
                    ctx: Optional[GrammarContext]) -> str:
    """The ONE clamp rule, shared by per-request resolution, header
    validation, and the response-cache scope: a ``background``-lane
    request (the TENANT_TIERS floor tier) or an explicit ``readonly``
    ask lowers the base profile to ``readonly``; nothing ever raises
    it. ``permissive`` (the A/B instrument) is never clamped."""
    if base == "permissive":
        return base
    requested = ctx.profile if ctx is not None else None
    if requested == "readonly" or lane == "background":
        return "readonly"
    return base


def validate_restriction(base: str, lane: Optional[str],
                         ctx: Optional[GrammarContext]) -> Optional[str]:
    """THE admission-time validation of a request's grammar intent,
    shared by the HTTP middleware and GrammarRuntime.validate_verbs so
    the two can never disagree. Returns an error string (HTTP 400) or
    None. Rules: the requested profile must be a known name; an
    allowed-verbs narrowing must stay inside the request's CLAMPED
    profile; and under the ``permissive`` base (the mask-everything
    A/B) verb restrictions are refused outright — permissive runs the
    unconstrained language, so the restriction could not be enforced,
    and a restriction the engine cannot enforce must never be silently
    dropped."""
    requested = (ctx.profile if ctx is not None else None)
    if requested is not None and requested not in PROFILES:
        return f"grammar profile must be one of {PROFILES}"
    verbs = ctx.allowed_verbs if ctx is not None else None
    if not verbs:
        return None
    name = clamped_profile(base, lane, ctx)
    if name == "permissive":
        return ("allowed-verbs cannot be enforced under the "
                "'permissive' grammar profile (it runs the "
                "unconstrained language)")
    bad = sorted(set(verbs) - set(profile_verbs(name)))
    if bad:
        return f"allowed-verbs {bad} not in the {name!r} grammar profile"
    return None


def cache_scope(base: str, lane: Optional[str],
                ctx: Optional[GrammarContext]) -> str:
    """Response-cache key suffix for one request's grammar identity.

    The query→command cache predates per-request grammars; without this
    scope a command generated under one tenant's grammar would be
    served verbatim to another — including an interactive tenant's
    MUTATING command served from cache to a readonly-clamped tenant,
    a clean bypass of the whole clamp. Empty when grammar is off (the
    pre-ISSUE-11 key, cache behaviour unchanged)."""
    prof = clamped_profile(base, lane, ctx)
    verbs = ""
    if ctx is not None and ctx.allowed_verbs:
        verbs = ",".join(sorted(ctx.allowed_verbs))
    return f"\x00grammar:{prof}:{verbs}"


class GrammarRuntime:
    """Compiled-variant registry + stacked device-table source."""

    def __init__(self, tokenizer: Tokenizer, vocab_size: int,
                 eos_ids: Sequence[int], *, profile: str = "default",
                 forced_run_min: int = 4, max_profiles: int = 6):
        if profile not in PROFILES:
            raise ValueError(
                f"GRAMMAR_PROFILE must be one of {PROFILES}, "
                f"got {profile!r}")
        self.tokenizer = tokenizer
        self.vocab_size = int(vocab_size)
        self.eos_ids = tuple(eos_ids)
        self.profile = profile
        self.forced_run_min = max(1, int(forced_run_min))
        self._lock = threading.Lock()
        self._fsms: Dict[int, TokenFSM] = {}
        self._keys: Dict[object, int] = {}
        self._base_dfa = build_kubectl_dfa(profile_verbs("default"))
        base_fsm = self._compile_named(profile)
        # Padding envelope: the full default grammar + margin (verb
        # subsets compile smaller; permissive is 2 states).
        if profile == "default":
            envelope = base_fsm
        else:
            envelope = compile_token_fsm(
                self._base_dfa, tokenizer, self.vocab_size, self.eos_ids)
        self.S_max = envelope.n_states + _STATE_MARGIN
        self.C_max = envelope.n_classes + _CLASS_MARGIN
        self.max_profiles = max(2, int(max_profiles))
        P, S, C = self.max_profiles, self.S_max, self.C_max
        self.tok_class = np.zeros((P, self.vocab_size), np.int32)
        self.class_ok = np.zeros((P * S, C), bool)
        self.class_next = np.zeros((P * S, C), np.int32)
        #: bumped on every install; engines compare against their last
        #: uploaded version to refresh device copies.
        self.version = 0
        self.fallbacks = 0     # variants rejected (overflow / no slot)
        self._install(("profile", profile), base_fsm)
        if profile != "readonly":
            self._install(("profile", "readonly"),
                          self._compile_named("readonly"))

    # ---------------------------------------------------------- compile

    def _compile_named(self, name: str) -> TokenFSM:
        if name == "permissive":
            return compile_permissive_fsm(self.vocab_size, self.eos_ids)
        return compile_token_fsm(
            build_kubectl_dfa(profile_verbs(name)), self.tokenizer,
            self.vocab_size, self.eos_ids)

    def _install(self, key, fsm: TokenFSM) -> Optional[int]:
        """Write one compiled variant into the next free profile slot.
        Caller holds the lock (or is the ctor). Returns the pid, or
        None when the variant does not fit the padded envelope / no
        slot is free."""
        if fsm.n_states > self.S_max or fsm.n_classes > self.C_max:
            self.fallbacks += 1
            return None
        pid = len(self._fsms)
        if pid >= self.max_profiles:
            self.fallbacks += 1
            return None
        S = self.S_max
        base = pid * S
        self.tok_class[pid, :] = 0
        self.tok_class[pid, :fsm.tok_class.shape[0]] = fsm.tok_class
        ns, nc = fsm.n_states, fsm.n_classes
        self.class_ok[base:base + S, :] = False
        self.class_next[base:base + S, :] = base + DEAD
        self.class_ok[base:base + ns, :nc] = fsm.class_ok
        self.class_next[base:base + ns, :nc] = base + fsm.class_next
        self._fsms[pid] = fsm
        self._keys[key] = pid
        self.version += 1
        return pid

    # ---------------------------------------------------------- resolve

    def resolve(self, lane: Optional[str] = None,
                ctx: Optional[GrammarContext] = None) -> int:
        """Profile id for one request. Clamp order: start from the
        configured base profile; a ``background``-lane request (the
        TENANT_TIERS floor tier) or an explicit ``readonly`` ask clamps
        to readonly; an allowed-verbs narrowing compiles/installs a
        variant (subset-validated by :meth:`validate_verbs` at the HTTP
        layer — unknown verbs never reach here). Falls back to the
        clamped named profile when the variant can't be installed."""
        name = clamped_profile(self.profile, lane, ctx)
        verbs = ctx.allowed_verbs if ctx is not None else None
        if verbs and name != "permissive":
            verbs = frozenset(verbs) & set(profile_verbs(name))
        with self._lock:
            base_pid = self._keys.get(("profile", name))
            if base_pid is None:     # readonly asked under readonly base
                base_pid = self._keys[("profile", self.profile)]
            if not verbs or name == "permissive":
                return base_pid
            key = ("verbs", name, verbs)
            pid = self._keys.get(key)
            if pid is not None:
                return pid
            if len(self._fsms) >= self.max_profiles:
                self.fallbacks += 1
                return base_pid
        # Compile OUTSIDE the lock: a cold variant compile takes seconds
        # at a 256k vocab, and holding the lock would stall every
        # concurrent cached-pid resolve meanwhile. (Callers with a
        # possibly-novel verb set additionally run resolve() off the
        # event loop — see the engines' submit paths.)
        fsm = compile_token_fsm(
            build_kubectl_dfa(sorted(verbs)), self.tokenizer,
            self.vocab_size, self.eos_ids)
        with self._lock:
            pid = self._keys.get(key)      # raced install: reuse theirs
            if pid is None:
                pid = self._install(key, fsm)
            return pid if pid is not None else base_pid

    def validate_verbs(self, verbs, lane: Optional[str] = None,
                       ctx: Optional[GrammarContext] = None) -> Optional[str]:
        """Admission-time validation of a per-request allowed-verbs
        restriction (delegates to the module-level rule the HTTP
        middleware also runs). Returns an error string (400) or None."""
        merged = GrammarContext(
            profile=ctx.profile if ctx is not None else None,
            allowed_verbs=frozenset(verbs))
        return validate_restriction(self.profile, lane, merged)

    # ------------------------------------------------------------ views

    def snapshot_tables(self) -> tuple:
        """(version, tok_class, class_ok, class_next) as a CONSISTENT
        copy taken under the install lock — an engine refreshing its
        device tables must never capture a half-written variant row (a
        torn mask samples off-grammar tokens or wrongly dead-ends a
        slot) nor stamp a post-install version on pre-install contents.
        Copies are a few MB and only happen when the version moved."""
        with self._lock:
            return (self.version, self.tok_class.copy(),
                    self.class_ok.copy(), self.class_next.copy())

    def fsm(self, pid: int) -> TokenFSM:
        return self._fsms[pid]

    def start_state(self, pid: int) -> int:
        return pid * self.S_max + START

    def local(self, gs: int) -> tuple:
        return gs // self.S_max, gs % self.S_max

    def allowed_np(self, gs: int) -> np.ndarray:
        """[vocab] bool mask from a global state (host-side: the fake
        engine's stepping and the admission first-token mask)."""
        pid, s = self.local(gs)
        return self._fsms[pid].allowed(s)

    def advance(self, gs: int, tok: int) -> int:
        pid, s = self.local(gs)
        return pid * self.S_max + self._fsms[pid].advance(s, int(tok))

    def run(self, pid: int, ids: Sequence[int]) -> int:
        return pid * self.S_max + self._fsms[pid].run(ids)

    def is_dead(self, gs: int) -> bool:
        return gs % self.S_max == DEAD

    def forced_run(self, gs: int, cap: int) -> tuple:
        """(run_ids, ends_eos, end_gs) from a global state, honouring
        ``forced_run_min`` at the CALLER (this returns the raw chain —
        the scheduler compares it against in-flight speculation)."""
        pid, s = self.local(gs)
        run, ends_eos, end = self._fsms[pid].forced_run(s, cap)
        return run, ends_eos, pid * self.S_max + end

    def in_grammar(self, pid: int, ids: Sequence[int]) -> bool:
        return self._fsms[pid].in_grammar(ids)

    def health(self) -> dict:
        """Cheap /health section: which grammar this engine enforces."""
        base = self._keys[("profile", self.profile)]
        fsm = self._fsms[base]
        return {
            "enabled": True,
            "profile": self.profile,
            "grammar_hash": fsm.grammar_hash,
            "states": fsm.n_states,
            "classes": fsm.n_classes,
            "variants": len(self._fsms),
            "forced_run_min": self.forced_run_min,
            "variant_fallbacks": self.fallbacks,
        }
