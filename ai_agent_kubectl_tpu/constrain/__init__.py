"""Grammar-constrained decoding (ISSUE 11).

``grammar``  — the kubectl byte-level DFA (verbs, resource kinds, flag
               vocabulary, name character classes) and the safety
               cross-check.
``fsm``      — the tokenizer-composed token FSM (SGLang's compressed
               FSM: token equivalence classes + per-state legality)
               with precomputed forced runs.
``runtime``  — per-engine variant registry, per-request resolution
               (tenant-tier clamp, allowed-verbs narrowing), and the
               stacked fixed-shape device tables the decode chunk
               gathers from.
"""

from .grammar import (BLOCKED_VERBS, DEFAULT_VERBS, READONLY_VERBS,
                      assert_safety_consistent, build_kubectl_dfa,
                      profile_verbs, sample_accepted)
from .fsm import TokenFSM, compile_permissive_fsm, compile_token_fsm
from .runtime import (PROFILES, GrammarContext, GrammarRuntime,
                      cache_scope, clamped_profile, current_grammar,
                      use_grammar, validate_restriction)

__all__ = [
    "BLOCKED_VERBS", "DEFAULT_VERBS", "READONLY_VERBS", "PROFILES",
    "GrammarContext", "GrammarRuntime", "TokenFSM",
    "assert_safety_consistent", "build_kubectl_dfa", "cache_scope",
    "clamped_profile", "compile_permissive_fsm", "compile_token_fsm",
    "current_grammar", "profile_verbs", "sample_accepted", "use_grammar",
    "validate_restriction",
]
