"""The kubectl grammar: a byte-level DFA over the one command shape the
service is allowed to emit.

The service's entire product is a single ``kubectl ...`` line, yet until
ISSUE 11 the model decoded it unconstrained from the full vocab and
``server/safety.py`` rejected malformed output *post hoc*. This module
makes unsafe output unrepresentable instead: the grammar admits exactly

    "kubectl " verb (" " arg)*

where ``verb`` comes from an enumerated verb set (profile-dependent:
the read-only profile drops every mutating verb), the first argument of
core resource verbs must be an enumerated resource kind (optionally
``kind/name``), flags come from an enumerated long/short flag vocabulary
(``--flag``, ``--flag=value``, ``-n``), and free arguments (names,
namespaces, selector values) are drawn from conservative character
classes that exclude every shell metacharacter and quote. By
construction every accepted string passes ``server/safety.py`` — the
grammar ⊆ safety inclusion is asserted by a property test
(tests/test_grammar.py) and a boot-time cross-check
(:func:`assert_safety_consistent`).

The DFA is built host-side as plain dict tries, then frozen to a numpy
``[n_states, 256]`` byte-transition table (state 0 = DEAD, state 1 =
START). ``constrain/fsm.py`` composes it with a tokenizer into the
token-level FSM the decode chunk enforces on device.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

#: DFA state conventions (shared with fsm.py / runtime.py): the dead
#: state must be 0 so a zero-initialized table row is safely "reject".
DEAD = 0
START = 1

# --------------------------------------------------------------- verbs

#: read-only verbs: observation only — no write, no exec, no tunnel.
READONLY_VERBS = (
    "api-resources", "api-versions", "cluster-info", "describe", "diff",
    "explain", "get", "logs", "top", "version", "wait",
)

#: mutating verbs the DEFAULT profile additionally allows (cluster
#: writes a kubectl NL service legitimately performs).
MUTATING_VERBS = (
    "annotate", "apply", "autoscale", "cordon", "create", "delete",
    "drain", "expose", "label", "patch", "rollout", "run", "scale",
    "set", "taint", "uncordon",
)

#: verbs NO grammar profile may ever contain — they open interactive
#: shells or tunnels into the cluster (``server/safety.py`` blocks them
#: too; :func:`assert_safety_consistent` keeps the two lists honest).
BLOCKED_VERBS = (
    "attach", "cp", "debug", "edit", "exec", "port-forward", "proxy",
)

DEFAULT_VERBS = tuple(sorted(READONLY_VERBS + MUTATING_VERBS))

#: verbs whose FIRST argument must be an enumerated resource kind (or a
#: flag) — the shape "kubectl get pods ..." the service overwhelmingly
#: emits. Other verbs go straight to the generic argument machine
#: ("kubectl logs web-1", "kubectl version").
RESOURCE_VERBS = frozenset((
    "annotate", "apply", "autoscale", "create", "delete", "describe",
    "edit", "expose", "get", "label", "patch", "rollout", "scale",
    "set", "top", "wait",
))

#: resource kinds (singular, plural, and short forms).
RESOURCE_KINDS = (
    "all", "cj", "clusterrole", "clusterroles", "cm", "configmap",
    "configmaps", "cronjob", "cronjobs", "daemonset", "daemonsets",
    "deploy", "deployment", "deployments", "ds", "endpoints", "ep",
    "ev", "event", "events", "hpa", "ing", "ingress", "ingresses",
    "job", "jobs", "limitrange", "limits", "namespace", "namespaces",
    "netpol", "networkpolicies", "networkpolicy", "no", "node", "nodes",
    "ns", "po", "pod", "pods", "pv", "pvc", "persistentvolume",
    "persistentvolumeclaim", "persistentvolumeclaims",
    "persistentvolumes", "quota", "rc", "replicaset", "replicasets",
    "replicationcontroller", "replicationcontrollers",
    "resourcequota", "resourcequotas", "role", "rolebinding",
    "rolebindings", "roles", "rs", "sa", "secret", "secrets", "service",
    "serviceaccount", "serviceaccounts", "services", "statefulset",
    "statefulsets", "sts", "svc",
)

#: long flag vocabulary (the ``--`` prefix is structural, not listed).
LONG_FLAGS = (
    "all", "all-namespaces", "cascade", "containers", "container",
    "context", "cpu-percent", "current-replicas", "dry-run", "env",
    "field-selector", "filename", "follow", "force", "grace-period",
    "help", "ignore-not-found", "image", "kubeconfig", "labels",
    "limit", "max", "min", "name", "namespace", "no-headers",
    "output", "overwrite", "port", "previous", "record", "replicas",
    "resource-version", "restart", "revision", "selector", "show-labels",
    "since", "sort-by", "tail", "timeout", "to-revision", "type",
    "watch",
)

#: single-letter flags ("-n kube-system", "-o wide", "-f app.yaml").
SHORT_FLAGS = "AfhlnopRvw"

#: free-argument characters (names, namespaces, selector/flag values).
#: Deliberately excludes every ``server/safety.py`` forbidden
#: metacharacter (``; & | ` $ ( ) < >``), whitespace, and both quote
#: kinds — an accepted string can never fail shell lexing.
NAME_CHARS = (
    "abcdefghijklmnopqrstuvwxyz"
    "ABCDEFGHIJKLMNOPQRSTUVWXYZ"
    "0123456789"
    ".-_/:=,%+*[]{}!@^~"
)
_SAFETY_FORBIDDEN = ";&|`$()<>"
assert not set(NAME_CHARS) & set(_SAFETY_FORBIDDEN)
assert not set(NAME_CHARS) & set(" \t'\"")


@dataclass
class CharDFA:
    """Frozen byte-level DFA: ``next[state, byte]`` (0 = DEAD), the
    accept mask, and the identity hash of the grammar that built it."""

    next: np.ndarray          # [n_states, 256] int32
    accept: np.ndarray        # [n_states] bool
    grammar_hash: str         # 12-hex sha256 of the grammar content
    n_verbs: int

    @property
    def n_states(self) -> int:
        return int(self.next.shape[0])

    def run(self, data: bytes, state: int = START) -> int:
        for b in data:
            state = int(self.next[state, b])
            if state == DEAD:
                return DEAD
        return state


class _Builder:
    """Mutable trie/state builder frozen into a :class:`CharDFA`.

    States are dicts byte→state; building is pure host-side Python, so
    clarity beats speed (a full grammar compiles in milliseconds)."""

    def __init__(self):
        self.trans: List[Dict[int, int]] = [dict(), dict()]  # DEAD, START
        self.accept: set = set()

    def new_state(self) -> int:
        self.trans.append({})
        return len(self.trans) - 1

    def edge(self, src: int, ch: str, dst: Optional[int] = None) -> int:
        b = ord(ch)
        nxt = self.trans[src].get(b)
        if nxt is not None and dst is not None and nxt != dst:
            raise ValueError(f"conflicting edge from {src} on {ch!r}")
        if nxt is None:
            nxt = dst if dst is not None else self.new_state()
            self.trans[src][b] = nxt
        return nxt

    def literal(self, src: int, text: str) -> int:
        for ch in text:
            src = self.edge(src, ch)
        return src

    def char_loop(self, state: int, chars: str) -> None:
        for ch in chars:
            self.edge(state, ch, state)

    def freeze(self, grammar_hash: str, n_verbs: int) -> CharDFA:
        n = len(self.trans)
        nxt = np.zeros((n, 256), np.int32)
        for s, edges in enumerate(self.trans):
            for b, d in edges.items():
                nxt[s, b] = d
        acc = np.zeros((n,), bool)
        acc[sorted(self.accept)] = True
        acc[DEAD] = False
        return CharDFA(next=nxt, accept=acc, grammar_hash=grammar_hash,
                       n_verbs=n_verbs)


def grammar_hash(verbs: Iterable[str]) -> str:
    """12-hex identity of one grammar variant's full content — surfaces
    in /health so an operator can tell which grammar a replica runs."""
    h = hashlib.sha256()
    for part in ("v1", ",".join(sorted(verbs)), ",".join(RESOURCE_KINDS),
                 ",".join(LONG_FLAGS), SHORT_FLAGS, NAME_CHARS):
        h.update(part.encode())
        h.update(b"\x00")
    return h.hexdigest()[:12]


def build_kubectl_dfa(verbs: Iterable[str] = DEFAULT_VERBS) -> CharDFA:
    """Compile the kubectl grammar over ``verbs`` to a byte DFA.

    Shape: ``"kubectl " verb (" " arg)*`` with

    - arg after a RESOURCE_VERBS verb's first space: resource kind
      (optionally ``kind/name``) or a flag;
    - generic args: free name (NAME_CHARS+, not starting with ``-``) or
      a vocabulary flag (``--long``, ``--long=value``, ``-X``,
      ``-X=value``);
    - accept exactly after a complete verb, kind, name, flag, or value
      — never on a trailing space or bare dash, so every accepted
      string survives ``server/safety.py``'s strip + shlex checks.
    """
    verbs = tuple(sorted(set(verbs)))
    blocked = set(BLOCKED_VERBS) & set(verbs)
    if blocked:
        raise ValueError(
            f"grammar may not contain blocked verbs: {sorted(blocked)}")
    unknown = set(verbs) - set(DEFAULT_VERBS)
    if unknown:
        raise ValueError(f"unknown kubectl verbs: {sorted(unknown)}")
    b = _Builder()

    verb_start = b.literal(START, "kubectl ")

    # Shared argument machines. ``gen_arg``: start of a generic argument
    # (name or flag); ``res_arg``: start of the first argument after a
    # resource verb (resource kind or flag).
    gen_arg = b.new_state()
    res_arg = b.new_state()

    # Generic free name: NAME_CHARS+ (first char not '-').
    name_body = b.new_state()
    for ch in NAME_CHARS:
        if ch != "-":
            b.edge(gen_arg, ch, name_body)
    b.char_loop(name_body, NAME_CHARS)
    b.accept.add(name_body)
    b.edge(name_body, " ", gen_arg)

    # Flag values after '=': free value characters.
    value_body = b.new_state()
    b.char_loop(value_body, NAME_CHARS)
    b.accept.add(value_body)
    b.edge(value_body, " ", gen_arg)

    # Flag vocabulary, built once and shared by both argument-start
    # states (duplicating the trie would double the DFA for no language
    # difference).
    dash = b.edge(gen_arg, "-")
    b.edge(res_arg, "-", dash)
    dash2 = b.edge(dash, "-")
    for flag in LONG_FLAGS:
        end = b.literal(dash2, flag)
        b.accept.add(end)
        b.edge(end, " ", gen_arg)
        eq = b.edge(end, "=")
        for ch in NAME_CHARS:
            b.edge(eq, ch, value_body)
    for ch in SHORT_FLAGS:
        end = b.edge(dash, ch)
        b.accept.add(end)
        b.edge(end, " ", gen_arg)
        eq = b.edge(end, "=")
        for ch2 in NAME_CHARS:
            b.edge(eq, ch2, value_body)

    # Resource kinds (first arg of resource verbs): trie; a complete
    # kind accepts, continues into generic args, or takes "/name".
    for kind in RESOURCE_KINDS:
        end = b.literal(res_arg, kind)
        b.accept.add(end)
        b.edge(end, " ", gen_arg)
        slash = b.edge(end, "/")
        for ch in NAME_CHARS:
            if ch != "/":
                b.edge(slash, ch, name_body)

    # Verb trie.
    for verb in verbs:
        end = b.literal(verb_start, verb)
        b.accept.add(end)
        b.edge(end, " ", res_arg if verb in RESOURCE_VERBS else gen_arg)

    return b.freeze(grammar_hash(verbs), len(verbs))


def profile_verbs(profile: str) -> Tuple[str, ...]:
    """Verb set of a named grammar profile. ``default`` = read-only +
    mutating; ``readonly`` = observation only (the TENANT_TIERS clamp
    target); ``permissive`` is resolved by the runtime to a
    mask-everything FSM (A/B: constrained plumbing, unconstrained
    language) and has no verb set here."""
    if profile == "default":
        return DEFAULT_VERBS
    if profile == "readonly":
        return tuple(READONLY_VERBS)
    raise ValueError(f"unknown grammar profile {profile!r}")


def sample_accepted(dfa: CharDFA, seed: int, max_len: int = 96) -> str:
    """Draw one random accepted string (the safety property test's
    generator): random-walk the live edges, biased toward stopping once
    in an accept state, never entering DEAD."""
    rng = np.random.default_rng(seed)
    out: List[int] = []
    state = START
    for _ in range(max_len):
        if dfa.accept[state] and (len(out) >= max_len - 8
                                  or rng.random() < 0.18):
            break
        choices = np.nonzero(dfa.next[state] != DEAD)[0]
        if choices.size == 0:
            break
        byte = int(rng.choice(choices))
        out.append(byte)
        state = int(dfa.next[state, byte])
    # Walk back to the last accepting prefix (a mid-token stop is not a
    # sentence of the language).
    while out:
        s = dfa.run(bytes(out))
        if s != DEAD and dfa.accept[s]:
            break
        out.pop()
    return bytes(out).decode("ascii")


def assert_safety_consistent() -> None:
    """Boot-time cross-check (ISSUE 11 satellite): every verb
    ``server/safety.py`` blocks must be absent from every grammar
    profile — the grammar makes unsafe commands unrepresentable, and
    safety stays an outer ring that agrees with it."""
    from ..server import safety

    for profile in ("default", "readonly"):
        verbs = set(profile_verbs(profile))
        overlap = verbs & set(safety.BLOCKED_VERBS)
        if overlap:
            raise RuntimeError(
                f"grammar profile {profile!r} contains safety-blocked "
                f"verbs {sorted(overlap)} — the two lists must agree")
    missing = set(BLOCKED_VERBS) - set(safety.BLOCKED_VERBS)
    if missing:
        raise RuntimeError(
            f"safety.BLOCKED_VERBS is missing grammar-blocked verbs "
            f"{sorted(missing)} — defense-in-depth requires both rings")
