"""Model architecture configs.

One ``ModelConfig`` parameterizes every family in BASELINE.json's eval
matrix (Gemma-2B/7B, Llama-3-8B/70B, Mixtral-8x7B) plus tiny deterministic
test models. Family differences are expressed as data, not subclasses:

- Gemma:   (1+w) RMSNorm, sqrt(dim) embedding scale, GeGLU, tied embeddings,
           head_dim 256, MHA (7B) / MQA (2B)
- Llama-3: plain RMSNorm, SiLU-GLU, GQA 8 KV heads, theta 500k, untied
- Mixtral: Llama geometry + 8-expert top-2 MoE MLP
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, Optional, Tuple


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    dim: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    head_dim: int
    mlp_hidden: int
    rope_theta: float = 10000.0
    rms_eps: float = 1e-6
    rms_offset: float = 0.0          # 1.0 for Gemma's (1+w) norm
    activation: str = "silu"         # silu | gelu (Gemma uses gelu_tanh)
    tie_embeddings: bool = False
    embed_scale: bool = False        # Gemma multiplies embeddings by sqrt(dim)
    # MoE (0 experts = dense MLP)
    n_experts: int = 0
    experts_per_token: int = 0
    # Special tokens (tokenizer-dependent; defaults overridden per family)
    bos_id: int = 1
    eos_ids: Tuple[int, ...] = (2,)
    pad_id: int = 0
    max_seq_len: int = 8192

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def q_per_kv(self) -> int:
        return self.n_heads // self.n_kv_heads

    def param_count(self) -> int:
        """Approximate parameter count (embeddings + layers)."""
        embed = self.vocab_size * self.dim
        attn = self.n_layers * (
            self.dim * self.n_heads * self.head_dim          # wq
            + 2 * self.dim * self.n_kv_heads * self.head_dim  # wk, wv
            + self.n_heads * self.head_dim * self.dim         # wo
        )
        mlp_units = max(self.n_experts, 1)
        mlp = self.n_layers * mlp_units * 3 * self.dim * self.mlp_hidden
        router = self.n_layers * self.dim * self.n_experts
        norms = self.n_layers * 2 * self.dim + self.dim
        head = 0 if self.tie_embeddings else self.vocab_size * self.dim
        return embed + attn + mlp + router + norms + head


_CONFIGS: Dict[str, ModelConfig] = {}


def _register(cfg: ModelConfig) -> ModelConfig:
    _CONFIGS[cfg.name] = cfg
    return cfg


# --- Test models (deterministic, CPU-fast) ---
TOY_8M = _register(ModelConfig(
    name="toy-8m", vocab_size=512, dim=256, n_layers=4, n_heads=4,
    n_kv_heads=2, head_dim=64, mlp_hidden=704, max_seq_len=2048,
))
TOY_MOE = _register(ModelConfig(
    name="toy-moe", vocab_size=512, dim=256, n_layers=2, n_heads=4,
    n_kv_heads=2, head_dim=64, mlp_hidden=448, n_experts=4,
    experts_per_token=2, max_seq_len=2048,
))

# --- Gemma (HF: google/gemma-{2b,7b}-it) ---
GEMMA_2B = _register(ModelConfig(
    name="gemma-2b-it", vocab_size=256000, dim=2048, n_layers=18, n_heads=8,
    n_kv_heads=1, head_dim=256, mlp_hidden=16384, rms_offset=1.0,
    activation="gelu", tie_embeddings=True, embed_scale=True,
    bos_id=2, eos_ids=(1, 107), pad_id=0, max_seq_len=8192,
))
GEMMA_7B = _register(ModelConfig(
    name="gemma-7b-it", vocab_size=256000, dim=3072, n_layers=28, n_heads=16,
    n_kv_heads=16, head_dim=256, mlp_hidden=24576, rms_offset=1.0,
    activation="gelu", tie_embeddings=True, embed_scale=True,
    bos_id=2, eos_ids=(1, 107), pad_id=0, max_seq_len=8192,
))

# --- Llama 3 (HF: meta-llama/Meta-Llama-3-{8B,70B}-Instruct) ---
LLAMA3_8B = _register(ModelConfig(
    name="llama-3-8b-instruct", vocab_size=128256, dim=4096, n_layers=32,
    n_heads=32, n_kv_heads=8, head_dim=128, mlp_hidden=14336,
    rope_theta=500000.0, rms_eps=1e-5,
    bos_id=128000, eos_ids=(128001, 128009), pad_id=128001, max_seq_len=8192,
))
LLAMA3_70B = _register(ModelConfig(
    name="llama-3-70b-instruct", vocab_size=128256, dim=8192, n_layers=80,
    n_heads=64, n_kv_heads=8, head_dim=128, mlp_hidden=28672,
    rope_theta=500000.0, rms_eps=1e-5,
    bos_id=128000, eos_ids=(128001, 128009), pad_id=128001, max_seq_len=8192,
))

# --- Mixtral (HF: mistralai/Mixtral-8x7B-Instruct-v0.1) ---
MIXTRAL_8X7B = _register(ModelConfig(
    name="mixtral-8x7b-instruct", vocab_size=32000, dim=4096, n_layers=32,
    n_heads=32, n_kv_heads=8, head_dim=128, mlp_hidden=14336,
    rope_theta=1e6, rms_eps=1e-5, n_experts=8, experts_per_token=2,
    bos_id=1, eos_ids=(2,), pad_id=0, max_seq_len=32768,
))


def get_config(name: str, **overrides) -> ModelConfig:
    try:
        cfg = _CONFIGS[name]
    except KeyError:
        raise KeyError(
            f"Unknown model {name!r}; known: {sorted(_CONFIGS)}"
        ) from None
    return replace(cfg, **overrides) if overrides else cfg


def list_configs() -> Dict[str, ModelConfig]:
    return dict(_CONFIGS)
