"""Model families: a single parameterized decoder-only transformer
(RMSNorm + RoPE + GQA + SwiGLU [+ MoE]) covering Gemma, Llama-3 and
Mixtral (SURVEY.md §7 step 2), plus weight conversion from HF safetensors.
"""

from .config import ModelConfig, get_config, list_configs  # noqa: F401
