"""HF safetensors checkpoint → framework parameter conversion.

Maps HuggingFace Llama/Gemma/Mixtral checkpoints onto the layer-stacked
param pytree ``transformer.init_params`` defines (SURVEY.md §7 hard part
"weight conversion fidelity" — validated by logit-parity tests against the
``transformers`` reference implementations in tests/test_convert.py).

Layout notes:
- HF ``nn.Linear`` stores [out_features, in_features]; our matmuls are
  ``x @ w`` so every projection is transposed on load.
- Per-layer tensors are stacked along a leading ``n_layers`` axis (the scan
  layout), so conversion is stream-friendly: one layer at a time, never two
  copies of the full model in host RAM.
- HF Llama/Gemma/Mixtral all use the rotate-half RoPE convention, matching
  ``ops.rope.apply_rope`` — no head permutation needed.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

logger = logging.getLogger(__name__)


def _open_checkpoint(path: str | Path) -> Tuple[Callable[[str], np.ndarray], List[str]]:
    """Return (tensor_getter, key_list) over one or many .safetensors files."""
    from safetensors import safe_open

    path = Path(path)
    files = sorted(path.glob("*.safetensors")) if path.is_dir() else [path]
    if not files:
        raise FileNotFoundError(f"No .safetensors files under {path}")
    handles = [safe_open(str(f), framework="np") for f in files]
    index: Dict[str, Any] = {}
    for h in handles:
        for k in h.keys():
            index[k] = h
    keys = list(index)

    def get(key: str) -> np.ndarray:
        return index[key].get_tensor(key)

    return get, keys


def _to_dtype(x: np.ndarray, dtype) -> jnp.ndarray:
    return jnp.asarray(x).astype(dtype)


def convert_hf_checkpoint(
    cfg: ModelConfig,
    path: str | Path,
    dtype=jnp.bfloat16,
) -> Dict[str, Any]:
    """Convert an HF checkpoint directory/file to framework params."""
    get, keys = _open_checkpoint(path)
    pfx = "model." if any(k.startswith("model.") for k in keys) else ""
    L = cfg.n_layers

    def t(key: str) -> np.ndarray:  # transpose linear
        return get(key).T

    def stack(fn: Callable[[int], np.ndarray]) -> jnp.ndarray:
        return jnp.stack([_to_dtype(fn(i), dtype) for i in range(L)])

    layers: Dict[str, Any] = {
        "attn_norm": stack(lambda i: get(f"{pfx}layers.{i}.input_layernorm.weight")),
        "mlp_norm": stack(lambda i: get(f"{pfx}layers.{i}.post_attention_layernorm.weight")),
        "wq": stack(lambda i: t(f"{pfx}layers.{i}.self_attn.q_proj.weight")),
        "wk": stack(lambda i: t(f"{pfx}layers.{i}.self_attn.k_proj.weight")),
        "wv": stack(lambda i: t(f"{pfx}layers.{i}.self_attn.v_proj.weight")),
        "wo": stack(lambda i: t(f"{pfx}layers.{i}.self_attn.o_proj.weight")),
    }

    if cfg.is_moe:
        E = cfg.n_experts
        layers["router"] = stack(
            lambda i: t(f"{pfx}layers.{i}.block_sparse_moe.gate.weight")
        )
        # experts.{e}.w1 = gate [F, D], w3 = up [F, D], w2 = down [D, F]
        layers["w_gate"] = jnp.stack([
            jnp.stack([
                _to_dtype(t(f"{pfx}layers.{i}.block_sparse_moe.experts.{e}.w1.weight"), dtype)
                for e in range(E)
            ]) for i in range(L)
        ])
        layers["w_up"] = jnp.stack([
            jnp.stack([
                _to_dtype(t(f"{pfx}layers.{i}.block_sparse_moe.experts.{e}.w3.weight"), dtype)
                for e in range(E)
            ]) for i in range(L)
        ])
        layers["w_down"] = jnp.stack([
            jnp.stack([
                _to_dtype(t(f"{pfx}layers.{i}.block_sparse_moe.experts.{e}.w2.weight"), dtype)
                for e in range(E)
            ]) for i in range(L)
        ])
    else:
        layers["w_gate"] = stack(lambda i: t(f"{pfx}layers.{i}.mlp.gate_proj.weight"))
        layers["w_up"] = stack(lambda i: t(f"{pfx}layers.{i}.mlp.up_proj.weight"))
        layers["w_down"] = stack(lambda i: t(f"{pfx}layers.{i}.mlp.down_proj.weight"))

    params: Dict[str, Any] = {
        "embed": _to_dtype(get(f"{pfx}embed_tokens.weight"), dtype),
        "layers": layers,
        "final_norm": _to_dtype(get(f"{pfx}norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in keys:
            params["lm_head"] = _to_dtype(get("lm_head.weight").T, dtype)
        else:
            logger.warning("lm_head.weight absent; tying to embeddings")
            params["lm_head"] = params["embed"].T

    _validate_shapes(cfg, params)
    return params


def _validate_shapes(cfg: ModelConfig, params: Dict[str, Any]) -> None:
    d, hd, H, KV, L = cfg.dim, cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    expect = {
        ("embed",): (cfg.vocab_size, d),
        ("final_norm",): (d,),
        ("layers", "wq"): (L, d, H * hd),
        ("layers", "wk"): (L, d, KV * hd),
        ("layers", "wv"): (L, d, KV * hd),
        ("layers", "wo"): (L, H * hd, d),
    }
    for keypath, shape in expect.items():
        node: Any = params
        for k in keypath:
            node = node[k]
        if tuple(node.shape) != shape:
            raise ValueError(
                f"Checkpoint/config mismatch at {'.'.join(keypath)}: "
                f"got {tuple(node.shape)}, expected {shape} for {cfg.name}"
            )
