"""HF safetensors checkpoint → framework parameter conversion.

Maps HuggingFace Llama/Gemma/Mixtral checkpoints onto the layer-stacked
param pytree ``transformer.init_params`` defines (SURVEY.md §7 hard part
"weight conversion fidelity" — validated by logit-parity tests against the
``transformers`` reference implementations in tests/test_convert.py).

Layout notes:
- HF ``nn.Linear`` stores [out_features, in_features]; our matmuls are
  ``x @ w`` so every projection is transposed on load.
- Per-layer tensors are stacked along a leading ``n_layers`` axis (the scan
  layout), so conversion is stream-friendly: one layer at a time, never two
  copies of the full model in host RAM.
- HF Llama/Gemma/Mixtral all use the rotate-half RoPE convention, matching
  ``ops.rope.apply_rope`` — no head permutation needed.
"""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .config import ModelConfig

logger = logging.getLogger(__name__)


def _open_checkpoint(path: str | Path) -> Tuple[Callable[[str], np.ndarray], List[str]]:
    """Return (tensor_getter, key_list) over one or many .safetensors files."""
    from safetensors import safe_open

    path = Path(path)
    files = sorted(path.glob("*.safetensors")) if path.is_dir() else [path]
    if not files:
        raise FileNotFoundError(f"No .safetensors files under {path}")
    handles = [safe_open(str(f), framework="np") for f in files]
    index: Dict[str, Any] = {}
    for h in handles:
        for k in h.keys():
            index[k] = h
    keys = list(index)

    def get(key: str) -> np.ndarray:
        return index[key].get_tensor(key)

    return get, keys


def _to_dtype(x: np.ndarray, dtype) -> jnp.ndarray:
    return jnp.asarray(x).astype(dtype)


def convert_hf_checkpoint(
    cfg: ModelConfig,
    path: str | Path,
    dtype=jnp.bfloat16,
    quant: str = "",
    quantize_embed: bool = False,
) -> Dict[str, Any]:
    """Convert an HF checkpoint directory/file to framework params.

    ``quant`` ("" | "int8" | "int4"): quantize each projection DURING
    conversion, one layer at a time — the device never holds more than
    the (quantized) tree plus one layer's full-precision slice. Without
    this a 7B-class load would OOM a 16 GB chip before any post-hoc
    quantization could run: the bf16 tree alone is ~17 GB (VERDICT r4
    item 7 — the streaming-load + quantize transients at real size).
    int4 falls back per leaf to int8 where the kernel format can't tile
    (ops/quant4.py::pick_format). ``quantize_embed`` stores the
    embedding per-row int8 (the tied-head read halves).
    """
    get, keys = _open_checkpoint(path)
    pfx = "model." if any(k.startswith("model.") for k in keys) else ""
    L = cfg.n_layers

    def t(key: str) -> np.ndarray:  # transpose linear
        return get(key).T

    def _quantize_slice(w: jnp.ndarray):
        """One layer's projection slice -> quantized leaf (or passthrough)."""
        from ..ops.quant import quantize_int8
        from ..ops.quant4 import pick_format, quantize_int4

        if quant == "int4":
            fmt = (pick_format(w.shape[-2], w.shape[-1])
                   if w.ndim == 2 else None)
            if fmt is not None:
                return quantize_int4(w, group_in=fmt[0], block_out=fmt[1])
            return quantize_int8(w)
        if quant == "int8":
            return quantize_int8(w)
        return w

    def _stack_leaves(parts: List[Any]):
        """Stack per-layer leaves ([in, out] arrays or quantized
        dataclasses) along a new leading L axis."""
        first = parts[0]
        if isinstance(first, jnp.ndarray):
            return jnp.stack(parts)
        import dataclasses as _dc

        kw = {f.name: jnp.stack([getattr(p, f.name) for p in parts])
              for f in _dc.fields(first) if f.name in ("q", "scale", "s")}
        return _dc.replace(first, **kw)

    def stack(fn: Callable[[int], np.ndarray]) -> jnp.ndarray:
        return jnp.stack([_to_dtype(fn(i), dtype) for i in range(L)])

    def qstack(fn: Callable[[int], np.ndarray]):
        """Stream-quantizing stack for projection leaves: load one layer,
        quantize on device, free the full-precision slice."""
        return _stack_leaves(
            [_quantize_slice(_to_dtype(fn(i), dtype)) for i in range(L)])

    layers: Dict[str, Any] = {
        "attn_norm": stack(lambda i: get(f"{pfx}layers.{i}.input_layernorm.weight")),
        "mlp_norm": stack(lambda i: get(f"{pfx}layers.{i}.post_attention_layernorm.weight")),
        "wq": qstack(lambda i: t(f"{pfx}layers.{i}.self_attn.q_proj.weight")),
        "wk": qstack(lambda i: t(f"{pfx}layers.{i}.self_attn.k_proj.weight")),
        "wv": qstack(lambda i: t(f"{pfx}layers.{i}.self_attn.v_proj.weight")),
        "wo": qstack(lambda i: t(f"{pfx}layers.{i}.self_attn.o_proj.weight")),
    }

    if cfg.is_moe:
        E = cfg.n_experts

        def eslice_q(i: int, part: str):
            """One layer's [E, in, out] expert stack, quantized per
            (layer, expert) slice (int8 even under int4 — the MoE einsum
            epilogues are int8-shaped)."""
            from ..ops.quant import quantize_int8

            parts = []
            for e in range(E):
                w = _to_dtype(
                    t(f"{pfx}layers.{i}.block_sparse_moe.experts.{e}."
                      f"{part}.weight"), dtype)
                parts.append(quantize_int8(w) if quant else w)
            return _stack_leaves(parts)

        layers["router"] = stack(
            lambda i: t(f"{pfx}layers.{i}.block_sparse_moe.gate.weight")
        )
        # experts.{e}.w1 = gate [F, D], w3 = up [F, D], w2 = down [D, F]
        layers["w_gate"] = _stack_leaves(
            [eslice_q(i, "w1") for i in range(L)])
        layers["w_up"] = _stack_leaves(
            [eslice_q(i, "w3") for i in range(L)])
        layers["w_down"] = _stack_leaves(
            [eslice_q(i, "w2") for i in range(L)])
    else:
        layers["w_gate"] = qstack(lambda i: t(f"{pfx}layers.{i}.mlp.gate_proj.weight"))
        layers["w_up"] = qstack(lambda i: t(f"{pfx}layers.{i}.mlp.up_proj.weight"))
        layers["w_down"] = qstack(lambda i: t(f"{pfx}layers.{i}.mlp.down_proj.weight"))

    if quantize_embed and quant:
        from ..ops.quant import quantize_embed_int8

        # Row-chunked quantization straight off the host array: the full
        # f32 working copy never materializes (quantize_embed_int8
        # chunks), and the bf16 copy is freed immediately after.
        embed = quantize_embed_int8(
            _to_dtype(get(f"{pfx}embed_tokens.weight"), dtype))
    else:
        embed = _to_dtype(get(f"{pfx}embed_tokens.weight"), dtype)

    params: Dict[str, Any] = {
        "embed": embed,
        "layers": layers,
        "final_norm": _to_dtype(get(f"{pfx}norm.weight"), dtype),
    }
    if not cfg.tie_embeddings:
        if "lm_head.weight" in keys:
            params["lm_head"] = _quantize_slice(
                _to_dtype(get("lm_head.weight").T, dtype))
        else:
            logger.warning("lm_head.weight absent; tying to embeddings")
            # Reuse the already-loaded embedding when it is still a plain
            # array — re-reading the checkpoint's largest tensor would be
            # a redundant full transfer; only a per-row-quantized embed
            # (whose scales are row-wise, not column-wise) forces a
            # fresh full-precision read.
            if isinstance(embed, jnp.ndarray):
                params["lm_head"] = _quantize_slice(embed.T)
            else:
                params["lm_head"] = _quantize_slice(
                    _to_dtype(get(f"{pfx}embed_tokens.weight").T, dtype))

    _validate_shapes(cfg, params)
    return params


def _validate_shapes(cfg: ModelConfig, params: Dict[str, Any]) -> None:
    d, hd, H, KV, L = cfg.dim, cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.n_layers
    expect = {
        ("embed",): (cfg.vocab_size, d),
        ("final_norm",): (d,),
        ("layers", "wq"): (L, d, H * hd),
        ("layers", "wk"): (L, d, KV * hd),
        ("layers", "wv"): (L, d, KV * hd),
        ("layers", "wo"): (L, H * hd, d),
    }
    for keypath, shape in expect.items():
        node: Any = params
        for k in keypath:
            node = node[k]
        if tuple(node.shape) != shape:
            raise ValueError(
                f"Checkpoint/config mismatch at {'.'.join(keypath)}: "
                f"got {tuple(node.shape)}, expected {shape} for {cfg.name}"
            )
