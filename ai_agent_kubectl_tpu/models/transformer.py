"""Pure-JAX decoder-only transformer, parameterized by ``ModelConfig``.

Design (TPU-first, not a port — the reference has no model code at all,
SURVEY.md §3.5):

- **Functional**: parameters are a plain pytree; ``forward`` is a pure
  function of (params, tokens, positions, cache). No module framework —
  nothing between the code and XLA.
- **Layer-stacked + lax.scan**: per-layer params are stacked on a leading
  ``n_layers`` axis and the layer loop is a ``lax.scan``. One layer gets
  traced/compiled once regardless of depth — an 80-layer Llama-70B compiles
  in roughly the time of one layer, and XLA still overlaps per-layer
  collectives with compute.
- **Static shapes everywhere**: tokens are padded to bucket sizes; the KV
  cache is a fixed [L, B, S, KV, d] buffer with explicit write positions, so
  jit never recompiles across requests (SURVEY.md §7 hard part "continuous
  batching × jit").
- **Explicit positions**: RoPE and causal masks take absolute positions, so
  prefix-KV splicing and ragged decode are correct by construction.
- **bf16 params/activations, f32 softmax/norm accumulation.**

Attention backend is pluggable (``attn_impl``): "dense" (ops/attention.py
reference) or "flash" (Pallas, ops/flash_attention.py).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import dense_attention, dense_attention_quant
from ..ops.norms import rms_norm
from ..ops.quant import (QuantKV, embed_lookup, kv_quantize, qmatmul,
                         tied_head)
from ..ops.rope import apply_rope
from .config import ModelConfig

Params = Dict[str, Any]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """Contiguous per-slot KV cache.

    k, v:    [n_layers, batch, max_seq, n_kv_heads, head_dim] — either the
             model dtype, or ``QuantKV`` (int8 payload + per-(position,
             head) f32 scales) when built with ``kv_quant="int8"``
    lengths: [batch] — number of valid positions per slot
    """

    k: Any
    v: Any
    lengths: jnp.ndarray

    @classmethod
    def zeros(cls, cfg: ModelConfig, batch: int, max_seq: int,
              dtype=jnp.bfloat16, kv_quant: str = "") -> "KVCache":
        shape = (cfg.n_layers, batch, max_seq, cfg.n_kv_heads, cfg.head_dim)
        if kv_quant == "int8":
            def zq():
                return QuantKV(q=jnp.zeros(shape, jnp.int8),
                               s=jnp.ones(shape[:-1], jnp.float32))

            return cls(k=zq(), v=zq(),
                       lengths=jnp.zeros((batch,), dtype=jnp.int32))
        return cls(
            k=jnp.zeros(shape, dtype=dtype),
            v=jnp.zeros(shape, dtype=dtype),
            lengths=jnp.zeros((batch,), dtype=jnp.int32),
        )

    @property
    def max_seq(self) -> int:
        leaf = self.k.q if isinstance(self.k, QuantKV) else self.k
        return leaf.shape[2]


# ----------------------------------------------------------------- init

def init_params(key: jax.Array, cfg: ModelConfig, dtype=jnp.bfloat16) -> Params:
    """Random init (scaled normal) with the layer axis stacked for scan."""

    def _dense_init(k, shape, scale):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * scale).astype(dtype)

    keys = iter(jax.random.split(key, 16))
    d, hd, H, KV, F, L = (cfg.dim, cfg.head_dim, cfg.n_heads, cfg.n_kv_heads,
                          cfg.mlp_hidden, cfg.n_layers)
    s_in = d ** -0.5
    s_mlp = F ** -0.5

    layers: Params = {
        "attn_norm": jnp.zeros((L, d), dtype) if cfg.rms_offset else jnp.ones((L, d), dtype),
        "wq": _dense_init(next(keys), (L, d, H * hd), s_in),
        "wk": _dense_init(next(keys), (L, d, KV * hd), s_in),
        "wv": _dense_init(next(keys), (L, d, KV * hd), s_in),
        "wo": _dense_init(next(keys), (L, H * hd, d), (H * hd) ** -0.5),
        "mlp_norm": jnp.zeros((L, d), dtype) if cfg.rms_offset else jnp.ones((L, d), dtype),
    }
    if cfg.is_moe:
        E = cfg.n_experts
        layers["router"] = _dense_init(next(keys), (L, d, E), s_in)
        layers["w_gate"] = _dense_init(next(keys), (L, E, d, F), s_in)
        layers["w_up"] = _dense_init(next(keys), (L, E, d, F), s_in)
        layers["w_down"] = _dense_init(next(keys), (L, E, F, d), s_mlp)
    else:
        layers["w_gate"] = _dense_init(next(keys), (L, d, F), s_in)
        layers["w_up"] = _dense_init(next(keys), (L, d, F), s_in)
        layers["w_down"] = _dense_init(next(keys), (L, F, d), s_mlp)

    params: Params = {
        "embed": _dense_init(next(keys), (cfg.vocab_size, d), 1.0),
        "layers": layers,
        "final_norm": jnp.zeros((d,), dtype) if cfg.rms_offset else jnp.ones((d,), dtype),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = _dense_init(next(keys), (d, cfg.vocab_size), s_in)
    return params


# ----------------------------------------------------- block-paged pool
#
# Pool-mode KV (ISSUE 10): the cache leaves are [n_layers, n_blocks,
# page, KV, hd] — one shared block pool instead of per-slot regions —
# and a per-slot block table [B, max_pages] maps sequence page p of slot
# b to pool block tables[b, p]. The helpers below are the only places
# the indirection lives: writes scatter through the table into the
# flattened pool (out-of-bounds rows — table sentinel or a False
# write_mask — drop, exactly like the dense path's OOB trick), reads
# gather each slot's pages back into the dense [B, kv_limit, KV, hd]
# view the existing attention backends consume. The TPU fast path skips
# the gather entirely (ops/paged_attention.py block-table kernel).


def _pool_flat_pos(tables, positions, page: int, n_blocks: int,
                   write_mask) -> jnp.ndarray:
    """[B, S] flat pool-row index per token; OOB (== n_blocks*page) for
    unmapped pages and masked rows, which the scatter drops."""
    pg = positions // page
    blk = jnp.take_along_axis(tables, pg, axis=1)
    flat = blk * page + positions % page
    oob = n_blocks * page
    flat = jnp.where(blk >= n_blocks, oob, flat)
    if write_mask is not None:
        # [B] gates whole rows (device-side termination); [B, S] gates
        # per token — ragged admission windows (ISSUE 19) write only
        # their first q_lens[b] columns.
        wm = write_mask if write_mask.ndim == 2 else write_mask[:, None]
        flat = jnp.where(wm, flat, oob)
    return flat


def _pool_scatter(leaf, flat, updates):
    """Scatter [B, S, ...] updates into a [n_blocks, page, ...] pool leaf
    at flat row indices (OOB drops)."""
    nb, page = leaf.shape[0], leaf.shape[1]
    f = leaf.reshape((nb * page,) + leaf.shape[2:])
    f = f.at[flat].set(updates.astype(leaf.dtype))
    return f.reshape(leaf.shape)


def _pool_gather(leaf, tables, n_pages: int):
    """Gather each slot's first ``n_pages`` pages into the contiguous
    [B, n_pages*page, ...] view dense/flash attention reads. Sentinel
    table entries clamp to a real block — those positions sit beyond the
    slot's live length, where the causal mask already excludes them."""
    idx = jnp.clip(tables[:, :n_pages], 0, leaf.shape[0] - 1)
    g = leaf[idx]
    return g.reshape((idx.shape[0], n_pages * leaf.shape[1])
                     + leaf.shape[2:])


# ------------------------------------------------- residual sharding
#
# f≈1 residual-path TP sharding (ISSUE 14): with weights Megatron-split
# over ``model``, the classic layout replicates the [B, S, d] residual
# on every TP shard — norms, RoPE epilogues, residual adds and the
# sampling scratch then run tp× redundantly, which is exactly the
# (1−f)·residual term tools/tp_projection.py prices. Pinning the
# residual batch-sharded over data×model at the sites below makes XLA
# fuse each row-parallel GEMM's all-reduce into a reduce-scatter at its
# output (plus one all-gather at the next column-parallel input): the
# elementwise segments between GEMMs run 1/tp-sized per shard and the
# collective count stays 2 fused pairs per layer — the projection's
# priced model. ``parallel/sharding.py::residual_spec`` owns the
# policy (and the pipe/expert/divisibility gates).


def _shard_residual(mesh, x: jnp.ndarray) -> jnp.ndarray:
    """Pin the [B, S, d] residual to the f≈1 layout (no-op when the
    policy doesn't apply to this mesh/shape). The named_scope is what
    lets obs/attribution.py bill the fused collectives XLA materializes
    at this boundary as the ``all_reduce`` category."""
    if mesh is None:
        return x
    from ..parallel.sharding import residual_spec

    spec = residual_spec(mesh, x.shape)
    if spec is None:
        return x
    from jax.sharding import NamedSharding

    with jax.named_scope("all_reduce"):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))


def _shard_logits(mesh, logits: jnp.ndarray) -> jnp.ndarray:
    """Pin [B, S, vocab] logits vocab-sharded over ``model`` (the LM
    head's natural output layout) so the head output and the sampling
    chain's vocab-sized scratch shard instead of replicating — the
    lm_head_sampling slice of the f≈1 residual. Sampling semantics are
    untouched: ``sample_tokens_seeded`` runs the same program over the
    sharded operand and draws the identical token (the byte-identity
    suites are the tripwire)."""
    if mesh is None:
        return logits
    from ..parallel.sharding import logits_spec

    spec = logits_spec(mesh, logits.shape[-1])
    if spec is None:
        return logits
    from jax.sharding import NamedSharding

    return jax.lax.with_sharding_constraint(
        logits, NamedSharding(mesh, spec))


# -------------------------------------------------------------- blocks

def _activation(cfg: ModelConfig, x: jnp.ndarray) -> jnp.ndarray:
    if cfg.activation == "gelu":
        return jax.nn.gelu(x, approximate=True)
    return jax.nn.silu(x)


def _dense_mlp(cfg: ModelConfig, lp: Params, x: jnp.ndarray) -> jnp.ndarray:
    gate = _activation(cfg, qmatmul(x, lp["w_gate"]))
    return qmatmul(gate * qmatmul(x, lp["w_up"]), lp["w_down"])


def _moe_mlp(cfg: ModelConfig, lp: Params, x: jnp.ndarray,
             mesh=None, token_mask=None,
             moe_impl: str = "auto") -> jnp.ndarray:
    """MoE MLP with impl selection (the seam VERDICT r2 item 2 asked for).

    ``moe_impl``:

    - ``auto``: the expert-parallel all-to-all dispatch
      (parallel/moe.py::expert_parallel_moe) whenever a mesh with a >1
      ``expert`` axis is in scope and the static shapes divide it;
      otherwise the dense all-experts evaluation — the single-device
      reference the EP path is parity-tested against.
    - ``ep``: ALWAYS the dispatch (requires a mesh with an ``expert``
      axis; ep=1 degenerates the all_to_alls to local copies) — how a
      single chip serves/benches the real dispatch path rather than the
      dense evaluation (VERDICT r4 item 3).
    - ``dense``: always the dense evaluation.

    The choice is static per compiled program (shapes and mesh are
    trace-time constants), so serving programs pay zero dispatch
    overhead. ``token_mask`` ([B, S], 0 = dead slot or bucket padding)
    keeps garbage tokens from consuming expert capacity.
    """
    from ..parallel.moe import dense_moe, expert_parallel_moe

    if moe_impl == "dense":
        return dense_moe(cfg, lp, x)
    if mesh is not None and "expert" in mesh.axis_names:
        ep = mesh.shape["expert"]
        B, S, _ = x.shape
        if ((moe_impl == "ep" or ep > 1)
                and (B * S) % ep == 0 and cfg.n_experts % ep == 0):
            # Decode steps (S == 1) have only a handful of live tokens per
            # shard; capacity_factor sizing there would make drops likely
            # under routing skew. capacity = T_local makes drops impossible
            # at negligible buffer cost, preserving single-device parity.
            capacity = (B * S) // ep if S == 1 else None
            return expert_parallel_moe(cfg, lp, x, mesh, capacity=capacity,
                                       token_mask=token_mask)
    if moe_impl == "ep":
        raise ValueError(
            "MOE_IMPL=ep needs a mesh with an expert axis whose size "
            "divides tokens and experts")
    return dense_moe(cfg, lp, x)


def _layer(cfg: ModelConfig, attn_impl: str, mesh, page_size: int,
           moe_impl: str,
           h: jnp.ndarray, lp: Params,
           layer_k: jnp.ndarray, layer_v: jnp.ndarray,
           positions: jnp.ndarray, kv_limit: int,
           batch_idx: jnp.ndarray,
           token_mask,
           write_mask=None,
           block_tables=None,
           q_lens=None) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One transformer block. Returns (h_out, new_layer_k, new_layer_v).

    The ``jax.named_scope`` blocks here (and in ``forward``/sampling) are
    zero-cost HLO metadata: XLA stamps each op's ``op_name`` with the
    scope path, which the profiler trace exports — the decode-step
    attribution tool (obs/attribution.py) bills device spans to op
    categories by these names instead of guessing from HLO op types.

    ``write_mask`` ([B] bool, decode only): rows whose mask is False skip
    the KV-cache scatter entirely — their write positions are pushed out
    of bounds, and OOB scatter updates are dropped by jax. This is how
    slots terminated mid-chunk by the device-resident done mask
    (engine/batcher.py) stop mutating their cache region instead of
    rewriting garbage at a frozen position every remaining step.
    """
    B, S, d = h.shape
    H, KV, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    with jax.named_scope("attn_norm"):
        x = rms_norm(h, lp["attn_norm"], cfg.rms_eps, cfg.rms_offset)
    with jax.named_scope("qkv_proj"):
        q = qmatmul(x, lp["wq"]).reshape(B, S, H, hd)
        k = qmatmul(x, lp["wk"]).reshape(B, S, KV, hd)
        v = qmatmul(x, lp["wv"]).reshape(B, S, KV, hd)
    with jax.named_scope("rope"):
        q = apply_rope(q, positions, cfg.rope_theta)
        k = apply_rope(k, positions, cfg.rope_theta)

    if block_tables is not None:
        # Block-paged pool (ISSUE 10): layer_k/v are [n_blocks, page, KV,
        # hd] pool slices; every KV write and read goes through the
        # per-slot block table. Same absolute-position semantics as the
        # dense path — only the storage addressing changes, so pool and
        # dense transcripts are bit-identical.
        is_q = isinstance(layer_k, QuantKV)
        pool_leaf = layer_k.q if is_q else layer_k
        page, n_blocks = pool_leaf.shape[1], pool_leaf.shape[0]
        if kv_limit % page:
            raise ValueError(
                f"pool kv_limit {kv_limit} not a multiple of page {page}")
        flat = _pool_flat_pos(block_tables, positions, page, n_blocks,
                              write_mask)
        with jax.named_scope("kv_write"):
            if is_q:
                qk, qv = kv_quantize(k), kv_quantize(v)
                layer_k = QuantKV(q=_pool_scatter(layer_k.q, flat, qk.q),
                                  s=_pool_scatter(layer_k.s, flat, qk.s))
                layer_v = QuantKV(q=_pool_scatter(layer_v.q, flat, qv.q),
                                  s=_pool_scatter(layer_v.s, flat, qv.s))
            else:
                layer_k = _pool_scatter(layer_k, flat, k)
                layer_v = _pool_scatter(layer_v, flat, v)
        n_pages = kv_limit // page
        kv_pos = jnp.arange(kv_limit)[None, None, :]
        mask = kv_pos <= positions[:, :, None]
        with jax.named_scope("attention"):
            if attn_impl == "ragged" and not is_q:
                # ONE kernel for every window shape (ISSUE 19): per-slot
                # q_len is 1 for decode, k+1 for spec verify, a prompt
                # span for (suffix) prefill — a mixed chunk is a single
                # dispatch. The scatter above already wrote the window's
                # own K/V into the pool, so the kernel reads everything
                # (context + window) through the block table; causal-in-
                # window masking gives column j exactly kv <= pos + j,
                # bitwise the gather path's semantics. int8 KV keeps the
                # loud gather fallback (is_q branch below) — the engine
                # resolves that regime at startup.
                ql = (jnp.full((B,), S, jnp.int32) if q_lens is None
                      else q_lens.astype(jnp.int32))
                if mesh is not None and mesh.shape["model"] > 1:
                    from ..ops.ragged_attention import \
                        ragged_attention_pool_sharded

                    attn = ragged_attention_pool_sharded(
                        q, layer_k, layer_v, ql, positions[:, 0],
                        block_tables, mesh, page_size=page)
                else:
                    from ..ops.ragged_attention import \
                        ragged_attention_pool

                    attn = ragged_attention_pool(
                        q, layer_k, layer_v, ql, positions[:, 0],
                        block_tables, page_size=page)
            elif attn_impl == "paged" and S == 1 and not is_q:
                # TPU fast path: the block-table pallas kernel reads only
                # each slot's live pages straight from the pool — no
                # gathered copy ever materializes. Under a >1 model axis
                # the kernel runs shard_mapped with Q and KV heads split
                # together (the pool shards on the KV-head axis, so each
                # shard holds whole KV groups — ISSUE 14); XLA can't
                # auto-partition a pallas_call.
                if mesh is not None and mesh.shape["model"] > 1:
                    from ..ops.paged_attention import \
                        paged_decode_attention_pool_sharded

                    attn = paged_decode_attention_pool_sharded(
                        q[:, 0], layer_k, layer_v, positions[:, 0],
                        block_tables, mesh, page_size=page)[:, None]
                else:
                    from ..ops.paged_attention import \
                        paged_decode_attention_pool

                    attn = paged_decode_attention_pool(
                        q[:, 0], layer_k, layer_v, positions[:, 0],
                        block_tables, page_size=page)[:, None]
            elif is_q:
                attn = dense_attention_quant(
                    q,
                    _pool_gather(layer_k.q, block_tables, n_pages),
                    _pool_gather(layer_k.s, block_tables, n_pages),
                    _pool_gather(layer_v.q, block_tables, n_pages),
                    _pool_gather(layer_v.s, block_tables, n_pages),
                    mask,
                )
            else:
                k_ctx = _pool_gather(layer_k, block_tables, n_pages)
                v_ctx = _pool_gather(layer_v, block_tables, n_pages)
                if attn_impl == "flash" and S > 1:
                    from ..ops.flash_attention import flash_attention_cached

                    attn = flash_attention_cached(q, k_ctx, v_ctx,
                                                  positions)
                else:
                    attn = dense_attention(q, k_ctx, v_ctx, mask)
        with jax.named_scope("o_proj"):
            h = _shard_residual(
                mesh, h + qmatmul(attn.reshape(B, S, H * hd), lp["wo"]))
        with jax.named_scope("mlp"):
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps, cfg.rms_offset)
            mlp = (_moe_mlp(cfg, lp, x, mesh, token_mask, moe_impl)
                   if cfg.is_moe else _dense_mlp(cfg, lp, x))
        return _shard_residual(mesh, h + mlp), layer_k, layer_v

    # Write this chunk's K/V into the cache at its absolute positions.
    # (scatter; positions are per-slot absolute indices). Dead rows
    # (write_mask False) scatter at an out-of-bounds position, which jax
    # drops — the cache row stays untouched.
    if write_mask is not None:
        _cap = (layer_k.q if isinstance(layer_k, QuantKV) else layer_k).shape[1]
        w_pos = jnp.where(write_mask[:, None], positions, _cap)
    else:
        w_pos = positions
    if isinstance(layer_k, QuantKV):
        # int8 KV: quantize the fresh chunk at write; the read span stays
        # int8 all the way into the attention dots —
        # dense_attention_quant commutes the per-(position, head) scales
        # onto the scores/probs, so only int8 bytes cross HBM for the
        # context (half the decode-attention traffic, half the pool) and
        # no dequantized copy ever materializes. The fresh chunk's own
        # k/v stay bf16 for the ring path.
        with jax.named_scope("kv_write"):
            qk, qv = kv_quantize(k), kv_quantize(v)
            layer_k = QuantKV(q=layer_k.q.at[batch_idx, w_pos].set(qk.q),
                              s=layer_k.s.at[batch_idx, w_pos].set(qk.s))
            layer_v = QuantKV(q=layer_v.q.at[batch_idx, w_pos].set(qv.q),
                              s=layer_v.s.at[batch_idx, w_pos].set(qv.s))
        if attn_impl == "paged" and S == 1:
            raise NotImplementedError(
                "paged decode attention does not read int8 KV; the engine "
                "resolves KV_QUANT=int8 to the dense KV ladder")
        with jax.named_scope("attention"):
            if attn_impl == "ring" and S > 1:
                # Ring prefill attends over the chunk's own fresh bf16 k/v
                # (no prior cache context); the quantized write above still
                # lands every position for later decode.
                from ..parallel.ring_attention import ring_attention

                attn = ring_attention(q, k, v, positions, mesh)
            else:
                kv_pos = jnp.arange(kv_limit)[None, None, :]
                mask = kv_pos <= positions[:, :, None]
                attn = dense_attention_quant(
                    q,
                    layer_k.q[:, :kv_limit], layer_k.s[:, :kv_limit],
                    layer_v.q[:, :kv_limit], layer_v.s[:, :kv_limit],
                    mask,
                )
        with jax.named_scope("o_proj"):
            h = _shard_residual(
                mesh, h + qmatmul(attn.reshape(B, S, H * hd), lp["wo"]))

        with jax.named_scope("mlp"):
            x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps, cfg.rms_offset)
            mlp = (_moe_mlp(cfg, lp, x, mesh, token_mask, moe_impl)
                   if cfg.is_moe else _dense_mlp(cfg, lp, x))
        return _shard_residual(mesh, h + mlp), layer_k, layer_v
    else:
        with jax.named_scope("kv_write"):
            layer_k = layer_k.at[batch_idx, w_pos].set(
                k.astype(layer_k.dtype))
            layer_v = layer_v.at[batch_idx, w_pos].set(
                v.astype(layer_v.dtype))
        k_ctx = layer_k[:, :kv_limit]
        v_ctx = layer_v[:, :kv_limit]
    # Causal mask over absolute positions (padding queries read garbage but
    # their outputs are never used).
    kv_pos = jnp.arange(kv_limit)[None, None, :]
    mask = kv_pos <= positions[:, :, None]

    if attn_impl == "paged" and S == 1:
        # Ragged decode: each slot reads only its live KV pages
        # (ops/paged_attention.py); kv_limit is irrelevant — cost tracks
        # positions per slot, not the bucket.
        from ..ops.paged_attention import paged_decode_attention

        def _paged(q1, k_all, v_all, pos1):
            return paged_decode_attention(q1, k_all, v_all, pos1,
                                          page_size=page_size)

        if mesh is not None and (mesh.shape["data"] > 1
                                 or mesh.shape["model"] > 1):
            # XLA can't auto-partition a pallas_call — shard_map it
            # explicitly: slots over ``data``, heads over ``model``
            # (VERDICT r3 weak #6). Three TP layouts, mirroring the dense
            # path's sanitize_spec policy:
            #   KV % tp == 0   → shard Q and KV heads together (grouping
            #                    stays aligned: each shard holds whole KV
            #                    groups, H/tp = G·KV/tp)
            #   KV == 1 (MQA)  → shard Q heads, the single KV head
            #                    replicated — every Q head maps to it
            #   else           → heads replicated (data-only). A replicated
            #                    KV>1 cache with sharded Q would need a
            #                    per-shard head offset the kernel doesn't
            #                    have (it recomputes G from local shapes),
            #                    silently mis-mapping Q→KV groups.
            import jax.sharding as jsh

            P_ = jsh.PartitionSpec
            dp, tp = mesh.shape["data"], mesh.shape["model"]
            d_ax = "data" if B % dp == 0 else None
            if KV % tp == 0:
                q_ax, kv_ax = "model", "model"
            elif KV == 1 and H % tp == 0:
                q_ax, kv_ax = "model", None
            else:
                q_ax, kv_ax = None, None
            from ..parallel.compat import shard_map

            with jax.named_scope("attention"):
                attn = shard_map(
                    _paged, mesh=mesh,
                    in_specs=(P_(d_ax, q_ax, None),
                              P_(d_ax, None, kv_ax, None),
                              P_(d_ax, None, kv_ax, None),
                              P_(d_ax)),
                    out_specs=P_(d_ax, q_ax, None),
                    axis_names={"data", "model"},
                    # pallas_call can't express per-axis varying metadata
                    # for the VMA checker; the specs above are the
                    # contract.
                    check_vma=False,
                )(q[:, 0], layer_k, layer_v, positions[:, 0])[:, None]
        else:
            with jax.named_scope("attention"):
                attn = _paged(q[:, 0], layer_k, layer_v,
                              positions[:, 0])[:, None]
    elif attn_impl == "ring" and S > 1:
        # Sequence-parallel self-attention over the chunk itself (no prior
        # cache context) — the from-scratch long-prefill path. K/V blocks
        # rotate over the ``seq`` mesh axis via ppermute; the cache write
        # above still lands every position for later decode.
        from ..parallel.ring_attention import ring_attention

        with jax.named_scope("attention"):
            attn = ring_attention(q, k, v, positions, mesh)
    elif attn_impl == "flash" and S > 1:
        from ..ops.flash_attention import flash_attention_cached

        with jax.named_scope("attention"):
            attn = flash_attention_cached(q, k_ctx, v_ctx, positions)
    else:
        with jax.named_scope("attention"):
            attn = dense_attention(q, k_ctx, v_ctx, mask)
    with jax.named_scope("o_proj"):
        h = _shard_residual(
            mesh, h + qmatmul(attn.reshape(B, S, H * hd), lp["wo"]))

    with jax.named_scope("mlp"):
        x = rms_norm(h, lp["mlp_norm"], cfg.rms_eps, cfg.rms_offset)
        mlp = (_moe_mlp(cfg, lp, x, mesh, token_mask, moe_impl) if cfg.is_moe
               else _dense_mlp(cfg, lp, x))
    return _shard_residual(mesh, h + mlp), layer_k, layer_v


# -------------------------------------------------------------- forward

def forward(
    params: Params,
    cfg: ModelConfig,
    tokens: jnp.ndarray,          # [B, S] int32
    positions: jnp.ndarray,       # [B, S] int32 absolute positions
    cache: KVCache,
    *,
    kv_limit: Optional[int] = None,   # static: attend over cache[:, :kv_limit]
    attn_impl: str = "dense",
    mesh=None,                        # static: enables EP MoE dispatch when
                                      # an "expert" axis >1 is present
    token_mask: Optional[jnp.ndarray] = None,  # [B, S]; 0 marks padding /
                                      # dead-slot tokens (MoE capacity)
    page_size: int = 128,             # static: KV page for attn_impl="paged"
    moe_impl: str = "auto",           # static: MoE dispatch policy
                                      # (auto | ep | dense; see _moe_mlp)
    logits_at: Optional[jnp.ndarray] = None,   # [B] int32: emit logits only
                                      # at this position per row
    write_mask: Optional[jnp.ndarray] = None,  # [B] bool: rows allowed to
                                      # write KV (device-side termination —
                                      # see _layer; ignored on the pipe
                                      # path, whose dead slots keep the
                                      # legacy frozen-position writes)
    block_tables: Optional[jnp.ndarray] = None,  # [B, max_pages] int32:
                                      # block-paged pool mode (ISSUE 10) —
                                      # cache leaves are [L, n_blocks,
                                      # page, ...] and every KV access
                                      # routes through the table; entries
                                      # >= n_blocks are the unmapped-page
                                      # sentinel (writes drop, reads are
                                      # causally masked)
    q_lens: Optional[jnp.ndarray] = None,  # [B] int32, attn_impl="ragged"
                                      # only: valid query columns per slot
                                      # (1=decode, k+1=spec verify,
                                      # span=prefill; 0 freezes). None =
                                      # all S columns valid. ISSUE 19.
) -> Tuple[jnp.ndarray, KVCache]:
    """Run the model over a token chunk (prefill: S>1; decode: S=1).

    Returns (logits [B, S, vocab], updated cache). ``cache.lengths`` is
    advanced by the number of *valid* tokens, which the caller tracks —
    here we set it to max(positions)+1 per slot (padding positions are
    clamped by the caller).

    ``logits_at`` gathers each row's hidden state at one position BEFORE
    the LM-head projection, returning [B, 1, vocab]. Prefill only ever
    consumes the last valid position's logits, and the head is ~20% of a
    2B prefill's FLOPs (bucket × dim × 256k-vocab) and its largest
    activation (bucket × vocab f32) — this turns both into 1/bucket of
    themselves.
    """
    if kv_limit is None:
        kv_limit = cache.max_seq
    B, S = tokens.shape
    batch_idx = jnp.arange(B)[:, None]

    # final_norm is always a plain array in the model dtype — it anchors
    # the activation dtype when the embedding is stored int8.
    with jax.named_scope("embed"):
        h = embed_lookup(params["embed"], tokens,
                         dtype=params["final_norm"].dtype)
        if cfg.embed_scale:
            h = h * jnp.asarray(cfg.dim ** 0.5, h.dtype)

    if (block_tables is not None and mesh is not None
            and "pipe" in mesh.axis_names and mesh.shape["pipe"] > 1):
        # The pipelined stage body (parallel/pipeline.py) has no block-
        # table plumbing — the engine resolves KV_POOL under a pipe mesh
        # to the dense ladder before ever tracing this. TP/EP meshes
        # compose (ISSUE 14): the pool cache shards on the KV-head axis
        # (parallel/sharding.py::pool_cache_specs) and every access
        # routes through the same table indirection as single-chip.
        raise NotImplementedError(
            "block-paged KV does not compose with a pipe mesh axis "
            "(no table plumbing in the stage body); use the dense "
            "KV ladder")
    # f≈1 residual sharding starts at the embedding output — the scan
    # carry then stays in the sharded layout across every layer.
    h = _shard_residual(mesh, h)
    if mesh is not None and "pipe" in mesh.axis_names and mesh.shape["pipe"] > 1:
        # Pipeline-parallel serving: the layer stack (params and KV cache
        # sharded over ``pipe`` on the layer axis, parallel/sharding.py)
        # runs as a GPipe shard_map instead of the lax.scan — stages relay
        # hidden states over ICI via ppermute, TP stays automatic inside
        # each stage (parallel/pipeline.py). The Pallas flash/paged kernels
        # and ring attention don't compose with the stage body, so the
        # pipelined path always runs dense attention; MoE layers likewise
        # evaluate densely (no EP all-to-all inside a stage — the engine
        # warns at mesh setup when pp>1 meets an expert axis). int8 KV
        # (QuantKV) flows through: the stage body's cache ops are
        # tree-mapped and _layer's dense path dequantizes in-place
        # (VERDICT r4 item 2 — the 70B pp x tp config needs int8 KV most).
        from ..parallel.pipeline import pipeline_layers

        h, new_k, new_v = pipeline_layers(
            params["layers"], cfg, h, positions, cache.k, cache.v, mesh,
            kv_limit=kv_limit, attn_impl="dense",
        )
    else:
        step = partial(_layer, cfg, attn_impl, mesh, page_size, moe_impl)

        def scan_body(h, xs):
            lp, layer_k, layer_v = xs
            h, new_k, new_v = step(h, lp, layer_k, layer_v, positions, kv_limit,
                                   batch_idx, token_mask, write_mask,
                                   block_tables, q_lens)
            return h, (new_k, new_v)

        h, (new_k, new_v) = jax.lax.scan(
            scan_body, h, (params["layers"], cache.k, cache.v)
        )

    with jax.named_scope("final_norm"):
        h = rms_norm(h, params["final_norm"], cfg.rms_eps, cfg.rms_offset)
    if logits_at is not None:
        h = h[jnp.arange(B), logits_at][:, None]       # [B, 1, D]
    with jax.named_scope("lm_head"):
        if cfg.tie_embeddings:
            logits = tied_head(h, params["embed"])
        else:
            logits = qmatmul(h, params["lm_head"])
        # Keep the head's output in its vocab-sharded layout through the
        # sampling chain (f≈1: the [B, 256k] f32 scratch never
        # replicates; no-op off-mesh or when vocab doesn't divide).
        logits = _shard_logits(mesh, logits)

    if block_tables is not None:
        # Pool mode: lengths are per-SLOT host truth (the scheduler's
        # block tables track them); the pool cache's lengths leaf is
        # [n_blocks]-shaped and structural only.
        new_lengths = cache.lengths
    else:
        new_lengths = jnp.maximum(cache.lengths, positions.max(axis=1) + 1)
    return logits.astype(jnp.float32), KVCache(k=new_k, v=new_v, lengths=new_lengths)
