"""SLO burn-rate engine: multi-window error-budget accounting per lane.

``SLO_INTERACTIVE_MS`` gave the brownout controller a single p95 trigger;
operators paging on it still had to eyeball raw latency histograms to
answer "are we eating the month's error budget, and how fast?". This
module is the standard SRE answer: each latency sample (TTFT, queue
wait) is judged against its target at record time, and burn rate over
each configured window is

    burn = (breaching / total) / (1 - objective)

— burn 1.0 means "exactly spending budget at the sustainable rate",
above 1.0 the budget is being eaten faster than the objective allows
(the classic multi-window alert pairs a short window, fast detection,
with a long one, low noise). ``budget_remaining`` is the window's
unspent fraction, floored at 0.

Samples are judged at record time and accumulated into coarse TIME
BUCKETS per (slo, lane) — (total, breaching) pairs at a resolution of
one tenth of the shortest window — so memory and the per-probe scan are
bounded by the window geometry, not the request rate: at ANY traffic
level the 1h window really covers an hour (a bounded sample deque would
silently shrink the long window under exactly the high-traffic
conditions burn rates exist for). Window counts include the partial
bucket at the horizon — an error of at most one bucket width, i.e. the
stated resolution. No background thread; stdlib-only (the ``obs``
rule): the record path runs on the batch scheduler thread per
admission/finish.

The snapshot carries raw ``total``/``breaching`` counts per window, so
the fleet can merge N engines' snapshots by summing counts and
recomputing the rates (``merge_snapshots``) — burn rates themselves
don't average.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

#: the SLO metric names (``slo_*`` gauge label set — closed here so
#: cardinality is bounded by construction, like lanes and ledger classes).
SLO_TTFT = "ttft"
SLO_QUEUE_WAIT = "queue_wait"
#: turn-N TTFT for returning sessions (ISSUE 20): judged ONLY for
#: radix-warm re-admissions — the samples price what the two-tier KV
#: cache is for (a returning agent turn must not pay a cold re-prefill),
#: so cold first turns never dilute the burn rate.
SLO_SESSION_TTFT = "session_ttft"

#: validation cap on configured windows — each window is a label value
#: on every slo_* gauge, so the operator knob must not mint unbounded
#: series any more than a tenant may.
MAX_WINDOWS = 4


def window_label(secs: float) -> str:
    """``300 -> "5m"``, ``3600 -> "1h"`` — the human/metric label."""
    secs = int(secs)
    if secs % 3600 == 0:
        return f"{secs // 3600}h"
    if secs % 60 == 0:
        return f"{secs // 60}m"
    return f"{secs}s"


def parse_slo_windows(spec: str) -> Tuple[int, ...]:
    """``"300,3600"`` → (300, 3600). Ascending, positive, at most
    MAX_WINDOWS — a typo'd spec is a startup error, not a silently
    meaningless burn rate."""
    out: List[int] = []
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        secs = int(item)
        if secs <= 0:
            raise ValueError(f"SLO_WINDOWS entry must be > 0, got {item!r}")
        out.append(secs)
    if not out:
        raise ValueError("SLO_WINDOWS must name at least one window "
                         "(seconds, e.g. '300,3600')")
    if len(out) > MAX_WINDOWS:
        raise ValueError(
            f"SLO_WINDOWS allows at most {MAX_WINDOWS} windows "
            f"(each is a metric label value), got {len(out)}")
    if sorted(out) != out or len(set(out)) != len(out):
        raise ValueError(
            f"SLO_WINDOWS must be strictly ascending, got {spec!r}")
    return tuple(out)


class SloEngine:
    """Error-budget burn accounting for one engine instance.

    ``targets`` maps slo name → threshold ms (<= 0 disables that slo);
    ``objective`` is the success-rate objective the budget is priced
    from (0.99 → 1% of samples may breach)."""

    def __init__(self, targets: Dict[str, float], *,
                 objective: float = 0.99,
                 windows: Tuple[int, ...] = (300, 3600)):
        if not 0.0 < objective < 1.0:
            raise ValueError(
                f"SLO objective must be in (0, 1), got {objective}")
        self.targets = {name: float(ms) for name, ms in targets.items()
                        if float(ms) > 0}
        self.objective = float(objective)
        self.windows = tuple(int(w) for w in windows)
        # Bucket geometry: one tenth of the shortest window, so the
        # horizon-truncation error is ≤10% of the fast window; the ring
        # holds longest/width (+ slack) buckets regardless of rate.
        self._bucket_secs = max(1, (self.windows[0] // 10)
                                if self.windows else 1)
        self._max_buckets = ((self.windows[-1] // self._bucket_secs) + 2
                             if self.windows else 1)
        self._lock = threading.Lock()
        # (slo, lane) -> {bucket_index: [total, breaching]}; plus
        # lifetime counters so the metrics delta-mirror can expose a
        # monotone breach total.
        self._buckets: Dict[Tuple[str, str], Dict[int, List[int]]] = {}
        self._totals: Dict[Tuple[str, str], List[int]] = {}

    @property
    def enabled(self) -> bool:
        return bool(self.targets)

    # ------------------------------------------------------------ writing

    def note(self, slo: str, lane: str, value_ms: float,
             now: Optional[float] = None) -> None:
        """Judge one latency sample against its target. Free when the
        slo is disabled (target <= 0)."""
        target = self.targets.get(slo)
        if target is None:
            return
        now = time.monotonic() if now is None else now
        breached = value_ms > target
        key = (slo, lane)
        idx = int(now // self._bucket_secs)
        with self._lock:
            buckets = self._buckets.get(key)
            if buckets is None:
                buckets = self._buckets[key] = {}
                self._totals[key] = [0, 0]
            cell = buckets.get(idx)
            if cell is None:
                cell = buckets[idx] = [0, 0]
                # Amortized prune: drop buckets older than the longest
                # window once the ring overfills (bounds memory at any
                # request rate).
                if len(buckets) > self._max_buckets + 8:
                    floor = idx - self._max_buckets
                    for old in [b for b in buckets if b < floor]:
                        del buckets[old]
            cell[0] += 1
            if breached:
                cell[1] += 1
            tot = self._totals[key]
            tot[0] += 1
            if breached:
                tot[1] += 1

    # ------------------------------------------------------------ reading

    def _window_counts(self, buckets: Dict[int, List[int]], now: float,
                       window: int) -> Tuple[int, int]:
        """Sum buckets inside the window. The bucket containing the
        horizon is counted whole — at most one bucket width (a tenth of
        the fast window) of over-inclusion."""
        floor = int((now - window) // self._bucket_secs)
        total = breaching = 0
        for idx, (n, bad) in buckets.items():
            if idx >= floor:
                total += n
                breaching += bad
        return total, breaching

    def burn_rate(self, total: int, breaching: int) -> float:
        if total <= 0:
            return 0.0
        return (breaching / total) / (1.0 - self.objective)

    def fast_burn(self, slo: str, lane: str,
                  now: Optional[float] = None) -> Optional[float]:
        """Shortest-window burn rate for one (slo, lane) — the brownout
        controller's input signal. None when the slo is disabled or has
        no samples yet (an empty window must not read as 'healthy, raise
        shares' any more than as 'breaching')."""
        if slo not in self.targets or not self.windows:
            return None
        now = time.monotonic() if now is None else now
        with self._lock:
            buckets = self._buckets.get((slo, lane))
            if not buckets:
                return None
            total, breaching = self._window_counts(buckets, now,
                                                   self.windows[0])
        if total == 0:
            return None
        return self.burn_rate(total, breaching)

    def snapshot(self, now: Optional[float] = None) -> Dict[str, object]:
        """Full burn-rate view: slo → lane → per-window counts + rates.
        Raw counts ride along so fleet merges recompute rates from sums
        instead of averaging rates."""
        now = time.monotonic() if now is None else now
        out: Dict[str, object] = {
            "enabled": self.enabled,
            "objective": self.objective,
            "windows": [window_label(w) for w in self.windows],
            "slos": {},
        }
        with self._lock:
            keys = sorted(self._buckets)
            data = {k: dict(self._buckets[k]) for k in keys}
            totals = {k: tuple(self._totals[k]) for k in keys}
        for slo, target in sorted(self.targets.items()):
            lanes: Dict[str, object] = {}
            for (s, lane) in keys:
                if s != slo:
                    continue
                wins = {}
                for w in self.windows:
                    total, breaching = self._window_counts(
                        data[(s, lane)], now, w)
                    burn = self.burn_rate(total, breaching)
                    wins[window_label(w)] = {
                        "total": total,
                        "breaching": breaching,
                        "burn_rate": round(burn, 4),
                        "budget_remaining": round(max(0.0, 1.0 - burn), 4),
                    }
                seen, breached = totals[(s, lane)]
                lanes[lane] = {"windows": wins, "samples_total": seen,
                               "breaches_total": breached}
            out["slos"][slo] = {"target_ms": target, "lanes": lanes}
        return out


def merge_snapshots(snaps: List[Dict[str, object]]) -> Dict[str, object]:
    """Sum N engines' snapshots (fleet rollup): per-window counts add,
    burn rates recompute from the sums under the first snapshot's
    objective (replicas share one config)."""
    base = next((s for s in snaps if s and s.get("slos")), None)
    if base is None:
        return {}
    objective = float(base.get("objective", 0.99))
    out: Dict[str, object] = {
        "enabled": any(s.get("enabled") for s in snaps if s),
        "objective": objective,
        "windows": list(base.get("windows", [])),
        "slos": {},
    }
    denom = max(1e-9, 1.0 - objective)
    for s in snaps:
        for slo, body in ((s or {}).get("slos") or {}).items():
            dst = out["slos"].setdefault(
                slo, {"target_ms": body.get("target_ms"), "lanes": {}})
            for lane, row in (body.get("lanes") or {}).items():
                dl = dst["lanes"].setdefault(
                    lane, {"windows": {}, "samples_total": 0,
                           "breaches_total": 0})
                dl["samples_total"] += row.get("samples_total", 0)
                dl["breaches_total"] += row.get("breaches_total", 0)
                for label, win in (row.get("windows") or {}).items():
                    dw = dl["windows"].setdefault(
                        label, {"total": 0, "breaching": 0})
                    dw["total"] += win.get("total", 0)
                    dw["breaching"] += win.get("breaching", 0)
    for body in out["slos"].values():
        for row in body["lanes"].values():
            for win in row["windows"].values():
                burn = ((win["breaching"] / win["total"]) / denom
                        if win["total"] else 0.0)
                win["burn_rate"] = round(burn, 4)
                win["budget_remaining"] = round(max(0.0, 1.0 - burn), 4)
    return out
