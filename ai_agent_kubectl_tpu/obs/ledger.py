"""Goodput ledger: classify every device decode step a request cost.

The reliability machinery of PRs 4-7 deliberately burns device work —
masked wasted steps for dead slots, quarantine replays, preemption
exports, hedge losers, cross-replica migrations — and each subsystem
counts its own burn in its own counter. No surface answered the operator
question that matters at scale: *of every decode step the TPU executed,
how many became bytes a client kept, per tenant and lane?* This module
is that surface: one append-only ledger both engine schedulers (and the
fleet relay) feed at the exact points that already count these events,
with a hard conservation invariant — ``delivered`` plus every waste
class equals the total steps accounted.

Classes (closed set — Prometheus labels, cardinality bounded by
construction):

- ``delivered``       — decode steps whose token reached the client
                        (the goodput numerator; counted when a slot
                        finishes, from the emitted transcript)
- ``replayed``        — already-generated tokens re-derived by a
                        containment reset-and-replay or a cross-replica
                        migration re-splice (the recipient re-prefills
                        them — real device work that produced no new
                        client byte)
- ``preempted``       — generated tokens carried across a QoS
                        preempt-and-replay (same re-derivation cost,
                        different cause)
- ``hedge_loser``     — steps a losing hedge branch executed past the
                        shared resume prefix before it was cancelled
- ``wasted_masked``   — steps executed for already-terminated or freed
                        slots (the ``wasted_decode_steps_total`` family:
                        in-flight chunks dying by snapshot mismatch,
                        host-only finishes, legacy tail decode)
- ``quarantine_burn`` — tokens generated for a request that was then
                        terminally quarantined (its transcript is
                        discarded, never delivered)
- ``draft_rejected``  — speculative-decode draft proposals the 7B
                        verifier rejected (the 2B's step bought nothing;
                        the acceptance *rate* this implies is the
                        first-class /metrics signal of ISSUE 12)

Aggregation is per *lane* (the closed three-lane QoS set) for metrics,
and per *tenant* only in the ``/debug/ledger`` snapshot — tenants must
never become metric labels (the PR 7 cardinality rule). Tenant keys may
be API keys, so the ledger stores them **hashed** (``hash_tenant``), the
same form ``LOG_FORMAT=json`` stamps on log lines — a log grep and a
ledger row join on the same opaque key without either leaking the
credential.

Stdlib-only by design (same rule as the rest of ``obs``): the record
path is called from the batch scheduler thread per finish/waste event.
"""

from __future__ import annotations

import hashlib
import threading
from functools import lru_cache
from typing import Dict, List, Optional

#: the closed accounting-class set, goodput first.
CLASS_DELIVERED = "delivered"
CLASS_REPLAYED = "replayed"
CLASS_PREEMPTED = "preempted"
CLASS_HEDGE_LOSER = "hedge_loser"
CLASS_WASTED_MASKED = "wasted_masked"
CLASS_QUARANTINE_BURN = "quarantine_burn"
#: speculative decoding (ISSUE 12): draft-model proposals the verifier
#: rejected — the draft engine burned a step deriving a token the 7B
#: then re-sampled differently, so the work produced no client byte.
#: (Accepted drafts are the opposite: a transcript token that did NOT
#: cost its own target forward — they bill delivered like any other.)
CLASS_DRAFT_REJECTED = "draft_rejected"
LEDGER_CLASSES = (CLASS_DELIVERED, CLASS_REPLAYED, CLASS_PREEMPTED,
                  CLASS_HEDGE_LOSER, CLASS_WASTED_MASKED,
                  CLASS_QUARANTINE_BURN, CLASS_DRAFT_REJECTED)
WASTE_CLASSES = LEDGER_CLASSES[1:]

#: tenant-table overflow bucket: past ``max_tenants`` distinct keys, new
#: tenants aggregate here instead of growing the dict without bound (an
#: IP-rotating flood must not turn the debug snapshot into the very
#: cardinality leak the metric rule exists to prevent).
OVERFLOW_TENANT = "~overflow"


@lru_cache(maxsize=4096)
def hash_tenant(tenant: Optional[str]) -> str:
    """Stable opaque key for a tenant (12 hex chars of sha256).

    Tenant keys are API keys or client IPs — neither may appear in a
    debug response or a log line. The same function stamps JSON log
    records, so ledger rows and log lines join on the hash. Cached:
    the log filter calls this per record and the ledger per billing
    event, always with a small recurring key set."""
    if not tenant:
        tenant = "anon"
    return hashlib.sha256(tenant.encode("utf-8", "surrogatepass")) \
        .hexdigest()[:12]


def _empty_row() -> Dict[str, int]:
    return {cls: 0 for cls in LEDGER_CLASSES}


class GoodputLedger:
    """Per-lane / per-tenant step accounting for one engine (or the
    fleet relay's own events).

    ``record`` is the single write path: it bills one class, one lane,
    one (hashed) tenant, and the independent ``total_steps`` counter in
    one locked step — ``conservation()`` then checks the books actually
    balance rather than asserting a tautology (a future call site that
    pokes a dict directly, or a torn merge, shows up as an imbalance
    instead of silently wrong goodput)."""

    def __init__(self, *, enabled: bool = True, max_tenants: int = 256):
        self.enabled = enabled
        self.max_tenants = max(1, int(max_tenants))
        self._lock = threading.Lock()
        self._totals: Dict[str, int] = _empty_row()
        self._lanes: Dict[str, Dict[str, int]] = {}
        self._tenants: Dict[str, Dict[str, int]] = {}
        self.total_steps = 0

    # ------------------------------------------------------------ writing

    def record(self, cls: str, n: int, *, lane: str = "interactive",
               tenant: Optional[str] = None) -> None:
        """Bill ``n`` steps to one class. Unknown classes are a
        programming error worth failing loudly in tests, not a metric
        label to mint — hence the ValueError."""
        if cls not in self._totals:
            raise ValueError(f"unknown ledger class {cls!r}; "
                             f"valid: {LEDGER_CLASSES}")
        if not self.enabled or n <= 0:
            return
        key = hash_tenant(tenant)
        with self._lock:
            self._totals[cls] += n
            self.total_steps += n
            row = self._lanes.get(lane)
            if row is None:
                row = self._lanes[lane] = _empty_row()
            row[cls] += n
            trow = self._tenants.get(key)
            if trow is None:
                if len(self._tenants) >= self.max_tenants:
                    key = OVERFLOW_TENANT
                    trow = self._tenants.get(key)
                if trow is None:
                    trow = self._tenants[key] = _empty_row()
            trow[cls] += n

    # ------------------------------------------------------------ reading

    @staticmethod
    def _derive(row: Dict[str, int]) -> Dict[str, object]:
        total = sum(row.get(cls, 0) for cls in LEDGER_CLASSES)
        delivered = row.get(CLASS_DELIVERED, 0)
        out: Dict[str, object] = dict(row)
        out["total"] = total
        out["goodput_pct"] = (round(100.0 * delivered / total, 2)
                              if total else None)
        return out

    def snapshot(self) -> Dict[str, object]:
        """Lane-aggregated view (what stats()/metrics consume — no
        tenants here by design)."""
        with self._lock:
            lanes = {lane: dict(row) for lane, row in self._lanes.items()}
            totals = dict(self._totals)
            total_steps = self.total_steps
        return {
            "enabled": self.enabled,
            "classes": totals,
            "lanes": {lane: self._derive(row)
                      for lane, row in sorted(lanes.items())},
            "total_steps": total_steps,
            **{k: v for k, v in self._derive(totals).items()
               if k in ("total", "goodput_pct")},
        }

    def tenant_snapshot(self) -> Dict[str, Dict[str, object]]:
        """Hashed-tenant view — served ONLY by /debug/ledger."""
        with self._lock:
            tenants = {t: dict(row) for t, row in self._tenants.items()}
        return {t: self._derive(row) for t, row in sorted(tenants.items())}

    def conservation(self) -> Dict[str, object]:
        """The invariant the acceptance bar names: delivered + every
        waste class == total accounted steps."""
        with self._lock:
            accounted = sum(self._totals.values())
            total = self.total_steps
        return {
            "total_steps": total,
            "accounted": accounted,
            "balanced": accounted == total,
        }


def merge_snapshots(snaps: List[Dict[str, object]]) -> Dict[str, object]:
    """Sum N engines' lane snapshots into one fleet view (the fleet
    relay's own hedge-loser ledger merges with its replicas')."""
    classes: Dict[str, int] = {cls: 0 for cls in LEDGER_CLASSES}
    lanes: Dict[str, Dict[str, int]] = {}
    total_steps = 0
    enabled = False
    for s in snaps:
        if not s:
            continue
        enabled = enabled or bool(s.get("enabled"))
        total_steps += int(s.get("total_steps", 0))
        for cls, n in (s.get("classes") or {}).items():
            if cls in classes:
                classes[cls] += int(n)
        for lane, row in (s.get("lanes") or {}).items():
            dst = lanes.setdefault(lane, _empty_row())
            for cls in LEDGER_CLASSES:
                dst[cls] += int(row.get(cls, 0))
    out = {
        "enabled": enabled,
        "classes": classes,
        "lanes": {lane: GoodputLedger._derive(row)
                  for lane, row in sorted(lanes.items())},
        "total_steps": total_steps,
    }
    derived = GoodputLedger._derive(classes)
    out["total"] = derived["total"]
    out["goodput_pct"] = derived["goodput_pct"]
    return out
