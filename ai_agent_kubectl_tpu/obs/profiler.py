"""On-demand ``jax.profiler`` capture for a live server.

``POST /debug/profile?seconds=N`` lands here: start a device trace into a
fresh directory, sleep N seconds while live traffic keeps decoding, stop,
and report the directory (TensorBoard-loadable, ``xprof`` readable). The
whole point is catching "why is decode slow *right now*" without
restarting the server with profiling baked in.

jax is imported lazily inside the capture — the obs package must stay
importable (and the fake/openai deployments must stay jax-free) when no
one ever asks for a profile.
"""

from __future__ import annotations

import asyncio
import logging
import os
import shutil
import tempfile
import time

logger = logging.getLogger(__name__)

#: traces are tens of MB each; keep the newest few and reap the rest.
KEEP_TRACES = 4

#: capture length clamp (seconds): long enough for a few decode chunks,
#: short enough that an operator typo can't profile for an hour.
MIN_SECONDS = 0.1
MAX_SECONDS = 30.0


def clamp_seconds(seconds: float) -> float:
    return min(max(float(seconds), MIN_SECONDS), MAX_SECONDS)


def trace_base_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "ai-agent-kubectl-tpu-traces")


def _reap_old(base: str) -> None:
    old = sorted(
        d for d in os.listdir(base) if os.path.isdir(os.path.join(base, d))
    )
    if len(old) > KEEP_TRACES:
        for d in old[:-KEEP_TRACES]:
            shutil.rmtree(os.path.join(base, d), ignore_errors=True)


async def capture(seconds: float) -> dict:
    """Run one profiler capture; returns ``{"trace_dir", "seconds"}``.

    The caller serializes captures (one at a time) — jax.profiler has one
    global trace session and a second start_trace would raise.
    """
    import jax

    seconds = clamp_seconds(seconds)
    base = trace_base_dir()
    os.makedirs(base, exist_ok=True)
    _reap_old(base)
    trace_dir = tempfile.mkdtemp(
        prefix=f"{time.strftime('%Y%m%d-%H%M%S')}-", dir=base
    )
    logger.info("profiler: capturing %.1fs device trace into %s",
                seconds, trace_dir)
    jax.profiler.start_trace(trace_dir)
    try:
        await asyncio.sleep(seconds)
    finally:
        jax.profiler.stop_trace()
    return {"trace_dir": trace_dir, "seconds": seconds}
