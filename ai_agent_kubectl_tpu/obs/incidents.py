"""Anomaly-triggered incident capture: when the service notices its own
regression, it files the evidence.

The debug surfaces built in PRs 2–14 are excellent *during* an incident
— if an operator is already at a terminal with the right curl lines.
What was missing is the 3 a.m. path: a step-time breach or an SLO burn
spike happens, nobody is watching, and by the time a human looks the
flight-recorder ring and the chunk log have rotated past the evidence.
This module closes that loop: a small closed set of **triggers** is
evaluated against the engine's cheap health views, and a firing trigger
assembles a bounded **incident bundle** — flight-recorder snapshot,
chunk-event ring, ledger/SLO/QoS/pool/spec/grammar/sharding health
sections, config fingerprint, weights version — into a ring served by
token-gated ``GET /debug/incidents[/{id}]``.

Triggers (closed set — they are metric labels):

- ``steptime_breach``     — the step-time sentinel's p99 breached its
                            baseline envelope (obs/steptime.py)
- ``slo_fast_burn``       — fast-window error-budget burn ≥
                            ``INCIDENT_BURN_THRESHOLD``
- ``quarantine_spike``    — new terminal quarantines since the last
                            evaluation
- ``grammar_dead_end_spike`` — new grammar dead-end freezes
- ``pool_exhausted``      — KV pool starvation truncated a slot
- ``breaker_open``        — the service circuit breaker opened
- ``host_tier_thrash``    — the two-tier KV pool is churning: pages
                            demoted to host RAM AND onloaded back at
                            matching rates since the last evaluation
                            (the working set no longer fits the device
                            tier — every admission pays tier traffic)

Safety property: **capture can never cascade during the incident it is
observing.** Each trigger has an independent cooldown
(``INCIDENT_COOLDOWN_SECS``); within it further firings are *counted*
(``suppressed``) but assemble nothing — a sustained fault produces a
bounded number of bundles no matter how long it lasts. Spike triggers
judge deltas from the previous evaluation, and the very first
evaluation only baselines (pre-existing quarantines are history, not an
incident).

Log join: every capture stamps its ``incident_id`` into a bounded
module-level window that ``logging_setup.RequestIdFilter`` reads — a
``LOG_FORMAT=json`` line emitted while the incident window is open
carries the same id as the bundle, the exact join pattern the hashed
tenant and request-id stamps already use.

Stdlib-only (the ``obs`` rule). The bundle *collector* is a callable
supplied by the service layer — this module owns trigger policy and
the ring, never HTTP or engine imports.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

TRIGGER_STEPTIME = "steptime_breach"
TRIGGER_BURN = "slo_fast_burn"
TRIGGER_QUARANTINE = "quarantine_spike"
TRIGGER_GRAMMAR = "grammar_dead_end_spike"
TRIGGER_POOL = "pool_exhausted"
TRIGGER_BREAKER = "breaker_open"
TRIGGER_HOST_THRASH = "host_tier_thrash"
TRIGGERS = (TRIGGER_STEPTIME, TRIGGER_BURN, TRIGGER_QUARANTINE,
            TRIGGER_GRAMMAR, TRIGGER_POOL, TRIGGER_BREAKER,
            TRIGGER_HOST_THRASH)

# ---------------------------------------------------------------------------
# Log-join stamp: the active incident window, readable by the log filter
# ---------------------------------------------------------------------------

_stamp_lock = threading.Lock()
_active_stamps: List[Tuple[float, str]] = []   # (expires_mono, incident_id)


def _note_incident(incident_id: str, until: float) -> None:
    with _stamp_lock:
        now = time.monotonic()
        _active_stamps[:] = [(t, i) for t, i in _active_stamps if t > now]
        _active_stamps.append((until, incident_id))
        del _active_stamps[:-8]    # bounded, newest-last


def current_incident_id(now: Optional[float] = None) -> Optional[str]:
    """Newest incident id whose stamp window is still open (None
    otherwise) — what LOG_FORMAT=json lines carry so logs and bundles
    join post-hoc."""
    now = time.monotonic() if now is None else now
    with _stamp_lock:
        live = [(t, i) for t, i in _active_stamps if t > now]
        return live[-1][1] if live else None


def _fast_burn(snap: Optional[dict]) -> Optional[float]:
    """Worst fast-window burn across every (slo, lane) of an
    ``slo_health()`` snapshot (the same derivation the rollout gate
    uses, kept local — obs must not import engine code). None = no
    samples."""
    if not snap:
        return None
    windows = snap.get("windows") or []
    if not windows:
        return None
    fast = windows[0]
    best: Optional[float] = None
    for body in (snap.get("slos") or {}).values():
        for row in (body.get("lanes") or {}).values():
            win = (row.get("windows") or {}).get(fast)
            if win and win.get("total"):
                burn = float(win.get("burn_rate", 0.0))
                best = burn if best is None else max(best, burn)
    return best


class IncidentManager:
    """Trigger evaluation + cooldowns + the bounded incident ring for
    one service instance."""

    def __init__(self, *, ring: int = 8, cooldown_secs: float = 60.0,
                 burn_threshold: float = 2.0,
                 thrash_min_blocks: int = 8,
                 stamp_secs: Optional[float] = None):
        self.ring_size = max(1, int(ring))
        self.cooldown_secs = max(0.0, float(cooldown_secs))
        self.burn_threshold = max(0.0, float(burn_threshold))
        # host_tier_thrash sensitivity: BOTH the demote and onload
        # deltas since the last evaluation must reach this many blocks
        # (0 disables). Churn is the conjunction — a one-way flow is
        # just warmup or drain, not thrash.
        self.thrash_min_blocks = max(0, int(thrash_min_blocks))
        # How long log lines keep joining a fresh bundle; defaults to
        # the cooldown (the window in which no NEW bundle can appear).
        self.stamp_secs = (self.cooldown_secs if stamp_secs is None
                           else max(0.0, float(stamp_secs)))
        self._lock = threading.Lock()
        self._ring: "OrderedDict[str, dict]" = OrderedDict()
        self._last_fire: Dict[str, float] = {}
        self._last_totals: Dict[str, object] = {}
        self.captured: Dict[str, int] = {}
        self.suppressed: Dict[str, int] = {}
        self._seq = 0

    # ---------------------------------------------------------- detection

    def _spike(self, key: str, total: int) -> int:
        """Delta of a cumulative counter since the last evaluation; the
        first evaluation only baselines (0 — pre-existing totals are
        history, not an incident)."""
        prev = self._last_totals.get(key)
        self._last_totals[key] = total
        if prev is None:
            return 0
        return max(0, total - int(prev))

    def detect(self, views: Dict[str, object]) -> List[Tuple[str, dict]]:
        """Evaluate every trigger against one round of health views:
        ``{"steptime", "slo", "kv_pool", "grammar", "breaker",
        "quarantined_total"}`` (any may be None). Returns the firing
        (trigger, detail) pairs — cooldowns are applied later, in
        ``maybe_capture``, so suppressed firings still count."""
        out: List[Tuple[str, dict]] = []
        st = views.get("steptime") or {}
        breaches = st.get("breaches") or []
        if breaches:
            out.append((TRIGGER_STEPTIME, {
                "breaches": list(breaches)[:8],
                "trips_total": st.get("trips_total", 0)}))
        if self.burn_threshold > 0:
            burn = _fast_burn(views.get("slo"))
            if burn is not None and burn >= self.burn_threshold:
                out.append((TRIGGER_BURN, {
                    "fast_burn": round(burn, 4),
                    "threshold": self.burn_threshold}))
        with self._lock:
            n = self._spike("quarantined",
                            int(views.get("quarantined_total") or 0))
            if n > 0:
                out.append((TRIGGER_QUARANTINE, {"new_quarantines": n}))
            g = views.get("grammar") or {}
            dead = sum((g.get("dead_ends_total") or {}).values())
            n = self._spike("dead_ends", int(dead))
            if n > 0:
                out.append((TRIGGER_GRAMMAR, {"new_dead_ends": n}))
            kv = views.get("kv_pool") or {}
            n = self._spike("pool_starved",
                            int(kv.get("starved_slots_total", 0) or 0))
            if n > 0:
                out.append((TRIGGER_POOL, {
                    "new_starved_slots": n,
                    "free_blocks": kv.get("free")}))
            host = kv.get("host_tier") or {}
            dn = self._spike("host_demoted",
                             int(host.get("demoted_total", 0) or 0))
            on = self._spike("host_onloaded",
                             int(host.get("onloaded_total", 0) or 0))
            if (self.thrash_min_blocks > 0
                    and min(dn, on) >= self.thrash_min_blocks):
                out.append((TRIGGER_HOST_THRASH, {
                    "demoted_delta": dn,
                    "onloaded_delta": on,
                    "host_used": host.get("used"),
                    "host_capacity": host.get("capacity"),
                    "threshold": self.thrash_min_blocks}))
            breaker = views.get("breaker")
            prev = self._last_totals.get("breaker")
            self._last_totals["breaker"] = breaker
            if breaker == "open" and prev != "open":
                out.append((TRIGGER_BREAKER, {"breaker": breaker}))
        return out

    # ------------------------------------------------------------ capture

    def evaluate(self, views: Dict[str, object],
                 collect: Callable[[], dict]) -> List[dict]:
        """One evaluation round: detect, then capture whatever passes
        its cooldown. Returns the NEW bundles (empty most rounds)."""
        out = []
        for trigger, detail in self.detect(views):
            bundle = self.maybe_capture(trigger, detail, collect)
            if bundle is not None:
                out.append(bundle)
        return out

    def maybe_capture(self, trigger: str, detail: dict,
                      collect: Callable[[], dict],
                      now: Optional[float] = None) -> Optional[dict]:
        """Assemble one bundle unless ``trigger`` is inside its
        cooldown (then count it suppressed and assemble NOTHING — the
        cooldown is what bounds capture overhead during the very
        incident being observed)."""
        if trigger not in TRIGGERS:
            raise ValueError(f"unknown incident trigger {trigger!r}; "
                             f"valid: {TRIGGERS}")
        now = time.monotonic() if now is None else now
        with self._lock:
            last = self._last_fire.get(trigger)
            if last is not None and now - last < self.cooldown_secs:
                self.suppressed[trigger] = \
                    self.suppressed.get(trigger, 0) + 1
                return None
            self._last_fire[trigger] = now
            self._seq += 1
            incident_id = f"inc-{int(time.time()) & 0xFFFFFF:06x}-" \
                          f"{self._seq:03d}"
        # Collection runs OUTSIDE the lock: it reads engine health
        # views and the flight recorder, which take their own locks.
        try:
            body = collect() or {}
        except Exception:   # pragma: no cover - defensive
            logger.exception("incident %s: bundle collection failed",
                             incident_id)
            body = {"collection_error": True}
        bundle = {
            "id": incident_id,
            "trigger": trigger,
            "detail": detail,
            "at": time.strftime("%Y-%m-%dT%H:%M:%S",
                                time.gmtime()) + "Z",
            **body,
        }
        with self._lock:
            self._ring[incident_id] = bundle
            while len(self._ring) > self.ring_size:
                self._ring.popitem(last=False)
            self.captured[trigger] = self.captured.get(trigger, 0) + 1
        _note_incident(incident_id, until=now + self.stamp_secs)
        # The warning itself carries the id through the log filter's
        # stamp, so even text-mode logs name the bundle.
        logger.warning("incident %s captured (trigger=%s): %s",
                       incident_id, trigger, detail)
        return bundle

    # ------------------------------------------------------------ reading

    def get(self, incident_id: str) -> Optional[dict]:
        with self._lock:
            return self._ring.get(incident_id)

    def list(self) -> List[dict]:
        """Newest-first index (summaries only — the detail route serves
        full bundles)."""
        with self._lock:
            entries = list(self._ring.values())
        entries.reverse()
        return [{"id": e["id"], "trigger": e["trigger"],
                 "at": e["at"], "detail": e.get("detail"),
                 "weights_version": e.get("weights_version")}
                for e in entries]

    def snapshot(self) -> dict:
        """Cheap summary for /health and the metrics mirror."""
        with self._lock:
            last = next(reversed(self._ring)) if self._ring else None
            return {
                "ring": len(self._ring),
                "ring_size": self.ring_size,
                "cooldown_secs": self.cooldown_secs,
                "captured_total": dict(self.captured),
                "suppressed_total": dict(self.suppressed),
                "last_incident_id": last,
            }
