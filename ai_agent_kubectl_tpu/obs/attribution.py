"""Decode-step cost attribution: profiler trace → per-op-category table.

Round 5 measured the Gemma-7B decode step at 33.3 ms (trace) of which
weights account for ~11.6 ms and attention ~2–3 ms — leaving the MAJORITY
of the step unattributed (VERDICT r5 weak #1). This module closes that
hole: it runs the engine-identical donated decode chunk under
``jax.profiler.trace``, parses the exported device-span timeline, and
bills every span to a named op category, so the table SUMS to the
measured step instead of waving at "~19 ms of non-weight work".

How spans get names worth billing: the model code is annotated with
``jax.named_scope`` blocks (models/transformer.py ``_layer``/``forward``,
engine/sampling.py, the batcher splice programs) whose scope paths XLA
stamps into each op's metadata — the profiler exports them on the op
events (``long_name``/``tf_op`` args), surviving fusion (a fusion's name
carries its root op's scope). Categorization is therefore keyword
matching on those scope paths first, HLO op-type heuristics second, and
an honest ``other_device`` bucket for what neither matches; device idle
inside the capture window lands in ``gaps`` (dispatch bubbles + fusion
boundaries). ``coverage_pct`` counts only the recognized categories —
the ≥90% acceptance bar means scope-tagged spans, not "everything we
couldn't name, summed".

Two entry points:

- ``run_attribution(...)`` — build the engine-identical chunk (same scan
  body, donation, sampling, masking as ``BatchedJaxEngine``), trace it,
  parse, validate, return the artifact dict. Used by
  ``tools/attribute_step.py`` and ``bench.py --phase attr7b``.
- ``attribute_trace(trace_dir, steps)`` — parse + categorize an existing
  trace directory (what ``POST /debug/profile`` captured, or a synthetic
  trace in tests).

jax is imported lazily inside the harness functions — the obs package
must stay importable (and the fake/openai deployments jax-free) when no
one ever attributes anything.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

SCHEMA_ID = "decode-step-attribution/v2"

#: category order is presentation order; "gaps" is computed (window −
#: device-busy union), everything else from span durations.
#: v2 (ISSUE 14) adds ``all_reduce``: the fused TP collectives
#: (reduce-scatter at the row-parallel GEMM outputs + all-gather at the
#: column-parallel inputs — the ``all_reduce`` named_scope in
#: models/transformer.py) were previously lumped into data_movement, so
#: the sharded step's comm time was invisible to
#: ``tools/attribute_step.py --check`` and tp_projection could never
#: reconcile its priced all-reduce term against a measurement.
CATEGORIES = (
    "weight_gemms",        # qkv/o/mlp/moe projections + embedding read
    "attention",           # score/probs dots over the live KV span
    "lm_head_sampling",    # 256k-vocab head projection + sampling chain
    "kv_write_splice",     # per-layer KV scatter + admission splices
    "norm_rope_residual",  # layernorms, RoPE, residual adds
    "all_reduce",          # TP collectives fused into the GEMM outputs
    "data_movement",       # copies, transposes, converts, layout changes
    "other_device",        # device-busy spans nothing above matched
    "gaps",                # device idle inside the capture window
)

#: scope-path keywords (from the jax.named_scope annotations), checked in
#: order — first hit wins. "attn_norm"/"mlp_norm" must land in norms, so
#: the norm rule precedes the weight-GEMM rule that would match their
#: enclosing "mlp" scope; the all_reduce scope precedes everything that
#: could match the constraint's enclosing o_proj/mlp scopes.
_SCOPE_RULES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("all_reduce", ("all_reduce",)),
    ("lm_head_sampling", ("lm_head", "sampling")),
    ("kv_write_splice", ("kv_write", "kv_splice", "splice")),
    ("attention", ("attention", "flash", "paged", "ring")),
    ("norm_rope_residual", ("attn_norm", "mlp_norm", "final_norm",
                            "rms_norm", "rope")),
    ("weight_gemms", ("qkv_proj", "o_proj", "mlp", "embed", "moe",
                      "expert")),
)

#: HLO op-name fallbacks for spans with no scope metadata (bare fusion
#: names, infeed/copy ops XLA inserts itself). Collective ops bill to
#: all_reduce (the comm category), never data_movement — partitioner-
#: emitted collectives don't always inherit the constraint's scope.
_HLO_RULES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    # comm first: "reduce-scatter" must never match the kv rule's bare
    # "scatter".
    ("all_reduce", ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute", "collective")),
    ("kv_write_splice", ("scatter", "dynamic-update-slice",
                         "dynamic_update_slice")),
    ("lm_head_sampling", ("rng", "sort", "top-k", "topk")),
    ("data_movement", ("copy", "transpose", "bitcast", "convert",
                       "reshape", "concatenate", "broadcast", "tuple",
                       "infeed", "outfeed", "slice", "pad", "iota")),
    ("weight_gemms", ("dot", "convolution", "gemm", "matmul")),
)


def categorize(text: str) -> str:
    """Category for one span, from its name + metadata text."""
    t = text.lower()
    for cat, keys in _SCOPE_RULES:
        if any(k in t for k in keys):
            return cat
    for cat, keys in _HLO_RULES:
        if any(k in t for k in keys):
            return cat
    return "other_device"


# ------------------------------------------------------------- trace parse

def _load_trace_events(trace_dir: str) -> List[dict]:
    """All traceEvents from every profile file under ``trace_dir``
    (``plugins/profile/<run>/*.trace.json[.gz]`` — the layout
    ``jax.profiler.trace`` writes)."""
    events: List[dict] = []
    patterns = (
        os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json.gz"),
        os.path.join(trace_dir, "plugins", "profile", "*", "*.trace.json"),
    )
    for pat in patterns:
        for path in sorted(glob.glob(pat)):
            if path.endswith(".gz"):
                with gzip.open(path, "rt") as f:
                    data = json.load(f)
            else:
                with open(path) as f:
                    data = json.load(f)
            events.extend(data.get("traceEvents", []))
    return events


def _select_device_spans(
        events: Iterable[dict]) -> Tuple[List[Tuple[float, float, str]], str]:
    """(spans, source) — (start_us, end_us, text) op-level spans.

    Device pids are those whose process_name mentions TPU (bench.py's
    proven heuristic for this toolchain). Trace rows are hierarchical
    (modules / ops / steps on different tids) and a plain sum
    double-counts chip time (the r5 TTFT lesson), so within each device
    pid only the op-level rows are kept: tids whose thread_name matches
    "XLA Ops" when present, else the single busiest tid.

    With no device pid at all (CPU backend — the CI dryrun), fall back to
    the host-side XLA op executions (events carrying an ``hlo_op`` arg):
    not chip time, but the same parse/categorize path runs end to end.
    ``source`` reports which was used: "tpu_device" | "host_xla_ops" |
    "none".
    """
    proc_names: Dict[int, str] = {}
    thread_names: Dict[Tuple[int, int], str] = {}
    complete: List[dict] = []
    for e in events:
        ph = e.get("ph")
        if ph == "M":
            if e.get("name") == "process_name":
                proc_names[e.get("pid")] = str(
                    e.get("args", {}).get("name", ""))
            elif e.get("name") == "thread_name":
                thread_names[(e.get("pid"), e.get("tid"))] = str(
                    e.get("args", {}).get("name", ""))
        elif ph == "X":
            complete.append(e)

    device_pids = {pid for pid, name in proc_names.items() if "TPU" in name}
    spans: List[Tuple[float, float, str]] = []
    if not device_pids:
        for e in complete:
            args = e.get("args", {}) or {}
            if "hlo_op" not in args:
                continue
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
            if dur <= 0.0:
                continue
            text = " ".join(
                [str(e.get("name", ""))]
                + [str(v) for v in args.values() if isinstance(v, str)]
            )
            spans.append((ts, ts + dur, text))
        return spans, ("host_xla_ops" if spans else "none")
    for pid in device_pids:
        pid_events = [e for e in complete if e.get("pid") == pid]
        op_tids = {
            tid for (p, tid), name in thread_names.items()
            if p == pid and "xla op" in name.lower()
        }
        if not op_tids:
            # No labelled op line: keep the busiest tid (op rows dominate
            # module/step summaries in total duration).
            per_tid: Dict[int, float] = {}
            for e in pid_events:
                per_tid[e.get("tid")] = (per_tid.get(e.get("tid"), 0.0)
                                         + float(e.get("dur", 0.0)))
            if not per_tid:
                continue
            op_tids = {max(per_tid, key=per_tid.get)}
        for e in pid_events:
            if e.get("tid") not in op_tids:
                continue
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
            if dur <= 0.0:
                continue
            args = e.get("args", {}) or {}
            text = " ".join(
                [str(e.get("name", ""))]
                + [str(v) for v in args.values() if isinstance(v, str)]
            )
            spans.append((ts, ts + dur, text))
    return spans, "tpu_device"


def _union_ms(intervals: List[Tuple[float, float]]) -> float:
    """Total length (ms) of the union of [start, end] microsecond
    intervals (overlap-safe — hierarchical rows must not double-count)."""
    total = 0.0
    end: Optional[float] = None
    for s, t in sorted(intervals):
        if end is None or s > end:
            total += t - s
            end = t
        elif t > end:
            total += t - end
            end = t
    return total / 1000.0


def attribute_trace(trace_dir: str, steps: int, *,
                    meta: Optional[dict] = None) -> dict:
    """Parse ``trace_dir`` and bill device time to categories.

    ``steps`` = decode steps executed inside the capture (reps ×
    chunk_len); per-step numbers divide by it. Returns the artifact dict
    (schema ``decode-step-attribution/v2``), NOT yet validated — callers
    run ``validate_attribution`` so a parse bug can't silently ship a
    malformed artifact.
    """
    if steps < 1:
        raise ValueError("steps must be >= 1")
    spans, span_source = _select_device_spans(_load_trace_events(trace_dir))

    per_cat: Dict[str, List[Tuple[float, float]]] = {c: [] for c in CATEGORIES}
    per_op: Dict[str, Dict[str, float]] = {c: {} for c in CATEGORIES}
    for ts, te, text in spans:
        cat = categorize(text)
        per_cat[cat].append((ts, te))
        op = text.split(" ", 1)[0] or "?"
        per_op[cat][op] = per_op[cat].get(op, 0.0) + (te - ts) / 1000.0

    all_iv = [(s, t) for s, t, _ in spans]
    busy_ms = _union_ms(all_iv)
    window_ms = ((max(t for _, t, _ in spans) - min(s for s, _, _ in spans))
                 / 1000.0) if spans else 0.0
    gaps_ms = max(window_ms - busy_ms, 0.0)

    # Coverage is the UNION of every recognized category's intervals, not
    # their sum: concurrently-executing spans (host-XLA fallback streams,
    # multi-device pids) can overlap ACROSS categories, and a sum would
    # push coverage past 100% of the wall window. On a serial device
    # stream union == sum, so the chip number is unchanged.
    recognized_iv: List[Tuple[float, float]] = []
    categories = []
    for cat in CATEGORIES:
        if cat == "gaps":
            ms = gaps_ms
        else:
            ms = _union_ms(per_cat[cat])
        if cat not in ("other_device", "gaps"):
            recognized_iv.extend(per_cat[cat])
        top = sorted(per_op[cat].items(), key=lambda kv: -kv[1])[:5]
        categories.append({
            "name": cat,
            "ms_per_step": round(ms / steps, 4),
            "pct_of_step": round(100.0 * ms / window_ms, 2) if window_ms
            else 0.0,
            "top_ops": [{"name": n, "ms_per_step": round(v / steps, 4)}
                        for n, v in top],
        })

    recognized_ms = min(_union_ms(recognized_iv), window_ms)
    out = {
        "schema": SCHEMA_ID,
        "steps_measured": steps,
        "span_source": span_source,
        "n_device_spans": len(spans),
        "wall_ms_total": round(window_ms, 3),
        "device_busy_ms_total": round(busy_ms, 3),
        "step_ms": round(window_ms / steps, 4),
        "device_busy_ms_per_step": round(busy_ms / steps, 4),
        "categories": categories,
        "coverage_pct": round(100.0 * recognized_ms / window_ms, 2)
        if window_ms else 0.0,
        "unattributed_ms_per_step": round(
            (window_ms - recognized_ms) / steps, 4),
    }
    out.update(meta or {})
    return out


def validate_attribution(obj: dict) -> None:
    """Schema check for the attribution artifact (CI gates on it so the
    trace-parse path can't rot). Raises ``ValueError`` on any violation."""
    if not isinstance(obj, dict):
        raise ValueError("artifact must be a dict")
    if obj.get("schema") != SCHEMA_ID:
        raise ValueError(f"schema must be {SCHEMA_ID!r}, "
                         f"got {obj.get('schema')!r}")
    if obj.get("span_source") not in ("tpu_device", "host_xla_ops", "none"):
        raise ValueError(f"bad span_source {obj.get('span_source')!r}")
    for key, typ in (("steps_measured", int), ("n_device_spans", int),
                     ("wall_ms_total", (int, float)),
                     ("device_busy_ms_total", (int, float)),
                     ("step_ms", (int, float)),
                     ("coverage_pct", (int, float)),
                     ("unattributed_ms_per_step", (int, float)),
                     ("categories", list)):
        if not isinstance(obj.get(key), typ):
            raise ValueError(f"missing/mistyped field {key!r}")
    names = []
    for cat in obj["categories"]:
        if not isinstance(cat, dict):
            raise ValueError("category entries must be dicts")
        if cat.get("name") not in CATEGORIES:
            raise ValueError(f"unknown category {cat.get('name')!r}")
        names.append(cat["name"])
        for key in ("ms_per_step", "pct_of_step"):
            if not isinstance(cat.get(key), (int, float)) or cat[key] < 0:
                raise ValueError(f"category {cat['name']}: bad {key!r}")
        if not isinstance(cat.get("top_ops"), list):
            raise ValueError(f"category {cat['name']}: top_ops must be a list")
    if names != list(CATEGORIES):
        raise ValueError(
            f"categories must be exactly {list(CATEGORIES)} in order, "
            f"got {names}")
    if not (0.0 <= obj["coverage_pct"] <= 100.0):
        raise ValueError("coverage_pct out of [0, 100]")
    # The table must SUM to the step: categories (incl. gaps/other) cover
    # the window, up to rounding. Only enforceable on a real device
    # stream — host_xla_ops spans (the CPU dryrun fallback) run
    # concurrently on the executor pool, so their per-category sums can
    # legitimately exceed wall time.
    total_pct = sum(c["pct_of_step"] for c in obj["categories"])
    if (obj["span_source"] == "tpu_device" and obj["wall_ms_total"] > 0
            and not (95.0 <= total_pct <= 105.0)):
        raise ValueError(
            f"category percentages sum to {total_pct:.1f}, not ~100 — "
            "the table no longer sums to the measured step")


def render_markdown(obj: dict) -> str:
    """PROFILE.md-ready table for one attribution artifact."""
    lines = [
        "| Category | ms/step | % of step | top ops |",
        "|---|---|---|---|",
    ]
    for cat in obj["categories"]:
        tops = ", ".join(
            f"{o['name']} {o['ms_per_step']:.3f}" for o in cat["top_ops"][:3]
        ) or "—"
        lines.append(
            f"| {cat['name']} | {cat['ms_per_step']:.3f} "
            f"| {cat['pct_of_step']:.1f}% | {tops} |"
        )
    lines.append(
        f"| **step total** | **{obj['step_ms']:.3f}** | 100% "
        f"| coverage {obj['coverage_pct']:.1f}%, "
        f"unattributed {obj['unattributed_ms_per_step']:.3f} ms/step |"
    )
    return "\n".join(lines)


# --------------------------------------------------- engine-identical chunk

def run_attribution(*, model: str = "gemma-7b-it", quant: str = "int8",
                    kv_quant: str = "int8", dtype: str = "bfloat16",
                    batch_size: int = 48, chunk_len: int = 16,
                    max_seq: int = 192, kv_limit: Optional[int] = None,
                    reps: int = 6, top_k: int = 0, top_p: float = 1.0,
                    keep_trace: bool = False) -> dict:
    """Trace the engine-identical batched decode chunk and attribute it.

    "Engine-identical" means the same compiled program shape the serving
    scheduler dispatches (``BatchedJaxEngine._start_blocking``'s
    ``batched_chunk``): a donated ``lax.scan`` of ``chunk_len`` steps —
    forward with a KV-bucket limit and active-slot masking, per-slot
    batched sampling, position advance — over an ``S_alloc``-deep slot
    cache, starting mid-life so every timed KV write stays in bounds.
    The first (compile) execution runs OUTSIDE the capture; ``reps``
    chained executions run inside it with one forced sync at the end, so
    the window is wall-to-wall decode.
    """
    import shutil
    import tempfile
    import time

    import jax
    import jax.numpy as jnp

    from ..engine.jax_engine import kv_bucket_ladder
    from ..models.config import get_config
    from ..models.transformer import KVCache, forward, init_params

    cfg = get_config(model)
    jdtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[dtype]
    if quant == "int8":
        from ..ops.quant import random_params_int8

        params = random_params_int8(jax.random.PRNGKey(0), cfg, dtype=jdtype,
                                    quantize_embed=True)
    else:
        params = init_params(jax.random.PRNGKey(0), cfg, dtype=jdtype)

    S_alloc = max_seq + chunk_len
    if kv_limit is None:
        kv_limit = kv_bucket_ladder(S_alloc)[-1]   # the serving top bucket

    # THE serving chunk body, not a copy: make_termination_chunk_fn is the
    # same builder BatchedJaxEngine compiles per KV bucket, so the traced
    # program is engine-identical by construction (only the forward
    # closure differs: single-device dense attention here).
    from ..engine.batcher import make_termination_chunk_fn

    def forward_step(params, tok, pos, cache, live):
        return forward(params, cfg, tok, pos, cache, kv_limit=kv_limit,
                       attn_impl="dense", token_mask=live[:, None],
                       write_mask=live)

    batched_chunk = make_termination_chunk_fn(
        forward_step, chunk_len, tuple(sorted(set(cfg.eos_ids))),
        top_k, top_p, vocab_size=cfg.vocab_size)

    fn = jax.jit(batched_chunk, donate_argnums=(1, 2, 3, 7, 8))

    N = batch_size
    if S_alloc < (reps + 2) * chunk_len + 1:
        raise ValueError(
            f"max_seq {max_seq} too short for reps={reps} × "
            f"chunk={chunk_len}: timed KV writes would run out of bounds "
            f"(silently dropped scatters time a step without its "
            f"cache-write traffic)")
    pos0 = max(0, min(320, S_alloc - (reps + 2) * chunk_len - 1))
    tok = jnp.zeros((N, 1), jnp.int32)
    pos = jnp.full((N, 1), pos0, jnp.int32)
    cache = KVCache.zeros(cfg, N, S_alloc, dtype=jdtype, kv_quant=kv_quant)
    seeds = jnp.zeros((N,), jnp.int32)
    no_corrupt = jnp.zeros((N,), jnp.bool_)
    temps = jnp.zeros((N,), jnp.float32)
    # All lanes force-live with an unreachable budget, and fresh all-live
    # carry state per dispatch: a sampled EOS from random-init weights
    # must not progressively park lanes and time a partially-masked step.
    force = jnp.ones((N,), jnp.bool_)
    budget = jnp.full((N,), 1 << 30, jnp.int32)

    def all_live():
        return jnp.ones((N,), jnp.bool_), jnp.zeros((N,), jnp.int32)

    def sync(x):
        jax.block_until_ready(x)
        import numpy as np

        leaf = jax.tree_util.tree_leaves(x)[0]
        np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))

    active, ngen = all_live()
    packed, tok, pos, cache, _, _ = fn(
        params, tok, pos, cache, seeds, temps, force, active,
        ngen, budget, no_corrupt)                         # compile + warm
    sync(packed)

    trace_dir = tempfile.mkdtemp(prefix="attr_step_")
    t0 = time.perf_counter()
    try:
        with jax.profiler.trace(trace_dir):
            for _ in range(reps):
                active, ngen = all_live()
                packed, tok, pos, cache, _, _ = fn(
                    params, tok, pos, cache, seeds, temps, force, active,
                    ngen, budget, no_corrupt)
            sync(packed)
        wall_s = time.perf_counter() - t0
        steps = reps * chunk_len
        out = attribute_trace(trace_dir, steps, meta={
            "model": cfg.name,
            "backend": jax.default_backend(),
            "quant": quant or "-",
            "kv_quant": kv_quant or "-",
            "dtype": dtype,
            "batch_size": N,
            "chunk_len": chunk_len,
            "max_seq": max_seq,
            "kv_limit": kv_limit,
            "reps": reps,
            "wall_ms_per_step_host": round(wall_s * 1000.0 / steps, 4),
        })
        if keep_trace:
            out["trace_dir"] = trace_dir
        return out
    finally:
        if not keep_trace:
            shutil.rmtree(trace_dir, ignore_errors=True)
