"""Step-time sentinel: always-on streaming digests of per-chunk step
time, with online regression detection against a baseline envelope.

Every prior observability layer answers a question about ONE request or
ONE scrape: attribution explains a step, the ledger bills it, the trace
times it. Nothing watched the step itself *over time* — a 20% step-time
regression from a bad checkpoint, a straggling replica, or a
speculative-decode acceptance collapse was invisible until a human ran
``bench.py``. This module is the missing signal: both engine schedulers
feed it one sample per decode-chunk cycle (and one per admission
prefill), keyed by ``(phase, bucket)``:

- ``phase`` — ``prefill`` (admission → first-token consume),
  ``decode`` (plain chunk cycle), ``spec_verify`` (speculative
  draft/verify chunk cycle). Closed set: these are Prometheus labels.
- ``bucket`` — the KV bucket the chunk ran at (decode) or the prefill
  bucket covering the prompt (prefill); the fake engine keys decode by
  its batch rung. Bounded by the engine's bucket ladders.

Per key the sentinel keeps a bounded ring of per-step milliseconds
(``window`` samples — memory is O(keys × window) floats), cumulative
counts, and a trailing tokens/sec rate per rung. ``snapshot()`` derives
p50/p95/p99 — the ``step_time_seconds{phase,bucket,quantile}`` gauges —
and judges each digest against its **baseline envelope**:

- a ``PERF_BASELINES`` file (JSON, seeded from the BENCH_r*.json
  numbers of record) supplies per-phase/per-bucket expected ms, or
- absent a file entry, the digest self-calibrates: the median of its
  first ``min_samples`` samples becomes the baseline (which is what
  lets the whole subsystem — including the regression trigger — run in
  tier-1 on the fake engine, whose μs-scale steps no TPU baseline
  could ever judge).

A digest **breaches** when its recent p99 exceeds ``factor ×
baseline`` with at least ``min_samples`` recorded. Breach transitions
count ``trips`` (edge-triggered — a sustained regression is one trip,
not one per scrape). The fleet merges per-replica snapshots with
replica attribution (``merge_snapshots``), which is also what makes a
straggling replica visible: its digests breach while its siblings'
don't. ``canary_vs_stable`` is the weight-rollout gate's optional
step-time verdict (engine/rollout.py, ``ROLLOUT_STEPTIME_GATE``).

Stdlib-only (the ``obs`` rule): ``note()`` runs on the batch scheduler
thread once per chunk cycle.
"""

from __future__ import annotations

import json
import statistics
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

#: the closed phase set (Prometheus label values).
PHASE_PREFILL = "prefill"
PHASE_DECODE = "decode"
PHASE_SPEC_VERIFY = "spec_verify"
STEP_PHASES = (PHASE_PREFILL, PHASE_DECODE, PHASE_SPEC_VERIFY)

#: default prefill-length buckets used to key prefill samples when the
#: caller has no bucket ladder of its own (the fake engine) — label
#: cardinality must be bounded by construction, never by prompt length.
DEFAULT_PREFILL_BUCKETS = (64, 128, 256, 512, 1024)


def prefill_bucket(n: int,
                   buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS) -> int:
    """Smallest bucket covering ``n`` tokens (the last bucket for
    anything larger) — the bounded label a prefill sample is keyed by."""
    for b in buckets:
        if n <= b:
            return int(b)
    return int(buckets[-1]) if buckets else int(n)


def load_baselines(path: str) -> Dict[str, Dict[str, float]]:
    """Parse a PERF_BASELINES file into ``{phase: {bucket|'default':
    ms}}``. The file is JSON with a ``step_time_ms`` table (extra keys —
    provenance, notes — are ignored); unknown phases and non-numeric
    entries are startup errors, not silently inert baselines."""
    with open(path) as f:
        data = json.load(f)
    table = data.get("step_time_ms")
    if not isinstance(table, dict) or not table:
        raise ValueError(
            f"PERF_BASELINES {path!r} needs a non-empty 'step_time_ms' "
            f"table ({{phase: {{bucket|'default': ms}}}})")
    out: Dict[str, Dict[str, float]] = {}
    for phase, row in table.items():
        if phase not in STEP_PHASES:
            raise ValueError(
                f"PERF_BASELINES phase {phase!r} is not one of "
                f"{STEP_PHASES}")
        if not isinstance(row, dict):
            raise ValueError(
                f"PERF_BASELINES[{phase!r}] must map bucket|'default' "
                f"to ms, got {type(row).__name__}")
        out[phase] = {}
        for bucket, ms in row.items():
            try:
                ms = float(ms)
            except (TypeError, ValueError):
                raise ValueError(
                    f"PERF_BASELINES[{phase!r}][{bucket!r}] must be a "
                    f"number of ms, got {ms!r}") from None
            if ms <= 0:
                raise ValueError(
                    f"PERF_BASELINES[{phase!r}][{bucket!r}] must be "
                    f"> 0 ms, got {ms}")
            out[phase][str(bucket)] = ms
    return out


def _quantile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank quantile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class _Digest:
    """One (phase, bucket) stream: bounded sample ring + counters +
    trailing token rate + baseline/breach state."""

    __slots__ = ("phase", "bucket", "ring", "count", "trips", "breached",
                 "baseline_ms", "baseline_source", "calib", "tokens")

    def __init__(self, phase: str, bucket: int, window: int,
                 file_baseline_ms: Optional[float]):
        self.phase = phase
        self.bucket = int(bucket)
        self.ring: deque = deque(maxlen=window)
        self.count = 0
        self.trips = 0
        self.breached = False
        self.baseline_ms = file_baseline_ms
        self.baseline_source = "file" if file_baseline_ms else None
        self.calib: Optional[List[float]] = (
            None if file_baseline_ms else [])
        self.tokens: deque = deque(maxlen=2048)   # (t, n) rate window


class StepTimeSentinel:
    """Bounded per-(phase, bucket) step-time digests + breach detection
    for one engine instance. Thread-safe: the scheduler thread writes,
    scrape/health threads read."""

    def __init__(self, *, enabled: bool = True, window: int = 256,
                 factor: float = 2.0, min_samples: int = 16,
                 baselines=None, rate_window_secs: float = 60.0,
                 min_breach_ms: float = 5.0):
        self.enabled = bool(enabled)
        self.window = max(8, int(window))
        self.factor = max(1.0, float(factor))
        self.min_samples = max(1, int(min_samples))
        # Absolute breach floor: p99 must ALSO exceed the baseline by
        # this many ms. A μs-scale digest (host-side fake steps, tiny
        # prefills) would otherwise trip on pure scheduler jitter —
        # factor × nothing is still nothing — while any real regression
        # against a ms-scale device baseline (20% of a 23 ms step is
        # already 4.7 ms) clears 5 ms without noticing the floor.
        self.min_breach_ms = max(0.0, float(min_breach_ms))
        self.rate_window_secs = max(1.0, float(rate_window_secs))
        if isinstance(baselines, str) and baselines:
            baselines = load_baselines(baselines)
        self.baselines: Dict[str, Dict[str, float]] = baselines or {}
        self._lock = threading.Lock()
        self._digests: Dict[Tuple[str, int], _Digest] = {}
        self.trips_total = 0

    # ------------------------------------------------------------ writing

    def _file_baseline(self, phase: str, bucket: int) -> Optional[float]:
        row = self.baselines.get(phase)
        if not row:
            return None
        return row.get(str(bucket), row.get("default"))

    def note(self, phase: str, bucket: int, seconds: float, *,
             steps: int = 1, tokens: int = 0,
             now: Optional[float] = None) -> None:
        """Record one sample: ``seconds`` of wall covering ``steps``
        device steps (a chunk cycle passes its token width so the
        stored unit is ms *per step*); ``tokens`` feeds the trailing
        tok/s rate for this rung."""
        if not self.enabled or seconds < 0:
            return
        if phase not in STEP_PHASES:
            raise ValueError(f"unknown step phase {phase!r}; "
                             f"valid: {STEP_PHASES}")
        now = time.monotonic() if now is None else now
        ms = seconds * 1000.0 / max(1, steps)
        key = (phase, int(bucket))
        with self._lock:
            d = self._digests.get(key)
            if d is None:
                d = self._digests[key] = _Digest(
                    phase, bucket, self.window,
                    self._file_baseline(phase, bucket))
            d.ring.append(ms)
            d.count += 1
            if tokens > 0:
                d.tokens.append((now, tokens))
            if d.calib is not None:
                # Self-calibration: the first min_samples samples set
                # the envelope (median — a single cold outlier must not
                # double the baseline).
                d.calib.append(ms)
                if len(d.calib) >= self.min_samples:
                    d.baseline_ms = float(statistics.median(d.calib))
                    d.baseline_source = "calibrated"
                    d.calib = None

    # ------------------------------------------------------------ reading

    def _tok_rate(self, d: _Digest, now: float) -> float:
        horizon = now - self.rate_window_secs
        total = sum(n for t, n in list(d.tokens) if t >= horizon)
        return total / self.rate_window_secs if total else 0.0

    def snapshot(self, now: Optional[float] = None) -> Dict[str, object]:
        """Digest table + breach verdicts. Judging happens here (and
        only here), so trips stay edge-triggered no matter how many
        surfaces read the snapshot concurrently."""
        now = time.monotonic() if now is None else now
        digests: Dict[str, dict] = {}
        breaches: List[dict] = []
        with self._lock:
            for (phase, bucket), d in sorted(self._digests.items()):
                vals = sorted(d.ring)
                p50 = _quantile(vals, 0.50)
                p95 = _quantile(vals, 0.95)
                p99 = _quantile(vals, 0.99)
                ready = (d.count >= self.min_samples
                         and d.baseline_ms is not None
                         and d.baseline_ms > 0)
                breach = bool(ready
                              and p99 > self.factor * d.baseline_ms
                              and p99 - d.baseline_ms
                              > self.min_breach_ms)
                if breach and not d.breached:
                    d.trips += 1
                    self.trips_total += 1
                d.breached = breach
                body = {
                    "phase": phase,
                    "bucket": bucket,
                    "count": d.count,
                    "p50_ms": round(p50, 4),
                    "p95_ms": round(p95, 4),
                    "p99_ms": round(p99, 4),
                    "baseline_ms": (round(d.baseline_ms, 4)
                                    if d.baseline_ms else None),
                    "baseline_source": d.baseline_source,
                    "tok_s": round(self._tok_rate(d, now), 2),
                    "breach": breach,
                    "trips": d.trips,
                }
                digests[f"{phase}/{bucket}"] = body
                if breach:
                    breaches.append({
                        "phase": phase, "bucket": bucket,
                        "p99_ms": body["p99_ms"],
                        "baseline_ms": body["baseline_ms"],
                        "factor": self.factor,
                    })
            trips_total = self.trips_total
        return {
            "enabled": self.enabled,
            "factor": self.factor,
            "min_samples": self.min_samples,
            "trips_total": trips_total,
            "digests": digests,
            "breaches": breaches,
        }


def merge_snapshots(snaps: List[Optional[dict]]) -> Dict[str, object]:
    """Fleet rollup of per-replica snapshots (list position = replica
    index). Quantiles don't merge, so the fleet digest per key reports
    the WORST replica's percentiles with counts/rates summed; breaches
    union with replica attribution — which is exactly how a straggler
    shows: its replica index on the breach while siblings stay clean."""
    out: Dict[str, object] = {"enabled": False, "trips_total": 0,
                              "digests": {}, "breaches": [],
                              "replicas": []}
    digests: Dict[str, dict] = {}
    for idx, s in enumerate(snaps):
        if not s:
            continue
        out["enabled"] = out["enabled"] or bool(s.get("enabled"))
        out["trips_total"] += int(s.get("trips_total", 0))
        rep_breaches = []
        for br in (s.get("breaches") or ()):
            tagged = dict(br, replica=idx)
            out["breaches"].append(tagged)
            rep_breaches.append(tagged)
        for key, d in (s.get("digests") or {}).items():
            dst = digests.get(key)
            if dst is None:
                digests[key] = dict(d, worst_replica=idx)
                continue
            dst["count"] = dst.get("count", 0) + d.get("count", 0)
            dst["tok_s"] = round(
                dst.get("tok_s", 0.0) + d.get("tok_s", 0.0), 2)
            dst["trips"] = dst.get("trips", 0) + d.get("trips", 0)
            dst["breach"] = bool(dst.get("breach") or d.get("breach"))
            if d.get("p99_ms", 0.0) > dst.get("p99_ms", 0.0):
                for k in ("p50_ms", "p95_ms", "p99_ms", "baseline_ms",
                          "baseline_source"):
                    dst[k] = d.get(k)
                dst["worst_replica"] = idx
        out["replicas"].append({
            "replica": idx,
            "trips_total": s.get("trips_total", 0),
            "breaches": rep_breaches,
            "digests": s.get("digests") or {},
        })
    out["digests"] = digests
    return out


def canary_vs_stable(canary: Optional[dict],
                     stables: List[Optional[dict]], *,
                     min_samples: int = 8) -> Optional[dict]:
    """Weight-rollout gate input: the canary's worst decode/spec_verify
    p95 ratio against the stable cohort's median p95 on the same
    (phase, bucket) key. None when no key has a meaningful sample on
    both sides — no data must not read as healthy OR as breaching
    (same rule as the burn gate)."""
    if not canary:
        return None
    worst: Optional[dict] = None
    for key, d in (canary.get("digests") or {}).items():
        if d.get("phase") not in (PHASE_DECODE, PHASE_SPEC_VERIFY):
            continue
        if d.get("count", 0) < min_samples or not d.get("p95_ms"):
            continue
        refs = []
        for s in stables:
            sd = ((s or {}).get("digests") or {}).get(key)
            if sd and sd.get("count", 0) >= min_samples \
                    and sd.get("p95_ms"):
                refs.append(float(sd["p95_ms"]))
        if not refs:
            continue
        ref = float(statistics.median(refs))
        if ref <= 0:
            continue
        ratio = float(d["p95_ms"]) / ref
        if worst is None or ratio > worst["ratio"]:
            worst = {"key": key, "canary_p95_ms": float(d["p95_ms"]),
                     "stable_p95_ms": round(ref, 4),
                     "ratio": round(ratio, 4)}
    return worst
