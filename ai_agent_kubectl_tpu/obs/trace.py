"""Request-lifecycle trace context: request ID + span API.

One ``Trace`` per HTTP request, created by the observability middleware
and finished when the response (or exception) leaves it. Spans carry
``time.monotonic()`` begin/end stamps relative to nothing — offsets are
computed against the trace's own t0 at serialization time, so clock
adjustments can never skew a timeline. Events are point-in-time
annotations ("admitted to slot 3", "breaker opened") recorded from
wherever the trace travels, including the batch scheduler thread — all
mutation goes through one lock.

Propagation is two-legged:

- **async leg** (middleware, cache, breaker, engine submit, executor):
  the ``ContextVar`` below. asyncio copies the context into every task,
  so ``current_trace()`` works anywhere downstream of the middleware on
  the event loop.
- **thread leg** (batch scheduler): ContextVars do not cross threads, so
  the engine's submit path captures ``current_trace()`` into the queued
  request object and the scheduler annotates that reference directly.
"""

from __future__ import annotations

import contextvars
import re
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

#: phase names admitted into the ``request_phase_seconds`` histogram.
#: A fixed allowlist, NOT whatever span names show up — a bug (or a
#: hostile client header echoed into a span) must never mint unbounded
#: Prometheus label values.
PHASES = (
    "validate",      # body parse + pydantic + sanitation
    "queue_wait",    # submit → admission into a decode slot
    "prefill",       # prompt prefill (admission latency on the batcher)
    "decode",        # token generation
    "detokenize",    # token → text + engine/event-loop handoff
    "safety",        # output parsing + safety validation
    "execute",       # kubectl subprocess run (/execute)
    "cache",         # response-cache lookup serving a hit
    "fallback",      # rule-based degraded generation
    "respond",       # response model build + serialization
)

_RID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def new_request_id() -> str:
    """16 hex chars — short enough to quote in a bug report, random
    enough that collisions inside one flight-recorder window are moot."""
    return uuid.uuid4().hex[:16]


def sanitize_request_id(raw: Optional[str]) -> Optional[str]:
    """Echo a client-supplied X-Request-ID only when it is boringly safe:
    ≤64 chars of [A-Za-z0-9._-]. Anything else (header injection, log
    forging, 4 KB of junk) is discarded and a fresh ID is minted."""
    if raw and _RID_RE.match(raw):
        return raw
    return None


class Span:
    """One named interval inside a trace. ``t0``/``t1`` are raw
    ``time.monotonic()`` stamps; offsets are derived at read time."""

    __slots__ = ("name", "t0", "t1", "meta")

    def __init__(self, name: str, t0: float, t1: float,
                 meta: Optional[Dict[str, Any]] = None):
        self.name = name
        self.t0 = t0
        self.t1 = max(t1, t0)
        self.meta = meta or {}

    @property
    def duration_ms(self) -> float:
        return (self.t1 - self.t0) * 1000.0


class Trace:
    """Span timeline + event log for one request."""

    def __init__(self, request_id: str, method: str = "", path: str = ""):
        self.request_id = request_id
        self.method = method
        self.path = path
        self.t0 = time.monotonic()
        self.wall_start = time.time()
        self.status: Optional[int] = None
        self.error: Optional[str] = None
        # outcome flags the flight recorder filters/surfaces on
        self.shed = False
        self.degraded = False
        self.from_cache = False
        self._t_end: Optional[float] = None
        self._spans: List[Span] = []
        self._events: List[tuple] = []
        self._links: List[tuple] = []
        self._lock = threading.Lock()

    # ------------------------------------------------------------ recording

    @contextmanager
    def span(self, name: str, **meta):
        t0 = time.monotonic()
        try:
            yield self
        finally:
            self.add_span(name, t0, time.monotonic(), **meta)

    def add_span(self, name: str, t0: float, t1: float, **meta) -> None:
        """Record an interval from explicit monotonic stamps — used when a
        phase's boundaries are known after the fact (e.g. queue/prefill/
        decode reconstructed from an EngineResult's timings)."""
        with self._lock:
            self._spans.append(Span(name, t0, t1, meta or None))

    def event(self, message: str, **meta) -> None:
        """Point-in-time annotation; safe from any thread."""
        with self._lock:
            self._events.append((time.monotonic(), message, meta or None))

    def link(self, link_type: str, **meta) -> None:
        """Causal span link: a handoff where this request's execution
        moved — preempted out of a slot, migrated off a replica, raced
        on a hedge branch, a loser branch cancelled. Links are what
        stitch ONE timeline out of a request that crossed scheduler
        boundaries: every engine annotates the same Trace object (same
        process, same monotonic clock, so offsets reconcile for free),
        and the links name which segment each stretch of events belongs
        to — including branches that lost and would otherwise vanish.
        Safe from any thread, like ``event``."""
        with self._lock:
            self._links.append((time.monotonic(), link_type, meta or None))

    def finish(self, status: Optional[int] = None,
               error: Optional[str] = None) -> None:
        if status is not None:
            self.status = status
        if error is not None:
            self.error = error
        self._t_end = time.monotonic()

    # -------------------------------------------------------------- reading

    @property
    def duration_ms(self) -> float:
        end = self._t_end if self._t_end is not None else time.monotonic()
        return (end - self.t0) * 1000.0

    def phase_durations(self) -> Dict[str, float]:
        """name → total ms (same-named spans merged), insertion-ordered."""
        out: Dict[str, float] = {}
        with self._lock:
            for s in self._spans:
                out[s.name] = out.get(s.name, 0.0) + s.duration_ms
        return out

    def server_timing(self) -> str:
        """RFC 8941 Server-Timing value: ``queue_wait;dur=1.2, ...``.
        Span names are from code (never client input), so no escaping."""
        return ", ".join(
            f"{name};dur={dur:.2f}"
            for name, dur in self.phase_durations().items()
        )

    def summary(self) -> Dict[str, Any]:
        return {
            "request_id": self.request_id,
            "method": self.method,
            "path": self.path,
            "status": self.status,
            "duration_ms": round(self.duration_ms, 3),
            "shed": self.shed,
            "degraded": self.degraded,
            "from_cache": self.from_cache,
            "error": self.error,
            "start_time": self.wall_start,
        }

    def to_dict(self) -> Dict[str, Any]:
        """Full timeline — what /debug/requests/{id} serves. Offsets are
        milliseconds from request start."""
        with self._lock:
            spans = [
                {
                    "phase": s.name,
                    "start_ms": round((s.t0 - self.t0) * 1000.0, 3),
                    "end_ms": round((s.t1 - self.t0) * 1000.0, 3),
                    "duration_ms": round(s.duration_ms, 3),
                    **({"meta": s.meta} if s.meta else {}),
                }
                for s in sorted(self._spans, key=lambda s: s.t0)
            ]
            events = [
                {
                    "offset_ms": round((t - self.t0) * 1000.0, 3),
                    "message": msg,
                    **({"meta": meta} if meta else {}),
                }
                for t, msg, meta in self._events
            ]
            links = [
                {
                    "offset_ms": round((t - self.t0) * 1000.0, 3),
                    "type": link_type,
                    **({"meta": meta} if meta else {}),
                }
                for t, link_type, meta in self._links
            ]
        d = self.summary()
        d["spans"] = spans
        d["events"] = events
        d["links"] = links
        return d


# --------------------------------------------------------------- context

_CURRENT: contextvars.ContextVar[Optional[Trace]] = contextvars.ContextVar(
    "ai_agent_kubectl_tpu_trace", default=None
)


def current_trace() -> Optional[Trace]:
    return _CURRENT.get()


@contextmanager
def use_trace(trace: Trace):
    token = _CURRENT.set(trace)
    try:
        yield trace
    finally:
        _CURRENT.reset(token)


def trace_event(message: str, **meta) -> None:
    """Annotate the active trace, if any — the no-trace case (unit tests
    driving a component directly, background threads) is free."""
    t = _CURRENT.get()
    if t is not None:
        t.event(message, **meta)
