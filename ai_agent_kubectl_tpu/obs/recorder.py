"""Flight recorder: ring buffer of the last N finished request traces.

The middleware hands every finished serving-path trace here — successes,
sheds, rate-limits, degraded fallbacks, errors — so "request X was slow
at 14:32" is answerable from ``/debug/requests/{id}`` minutes later
without having had debug logging on. Traces are snapshotted to plain
dicts at record time (the Trace object stays with the scheduler thread,
which may append late events the snapshot deliberately excludes).

Memory bound: N timelines of a few KB each — FLIGHT_RECORDER_SIZE=256
keeps it well under a few MB regardless of traffic.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from .trace import Trace


class FlightRecorder:
    def __init__(self, size: int = 256):
        self.size = max(1, int(size))
        self._buf: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()
        self.recorded = 0

    def record(self, trace: Trace) -> None:
        snapshot = trace.to_dict()
        with self._lock:
            # A replayed request ID (client retried with the same
            # X-Request-ID) overwrites — last flight wins, and the ring
            # never holds two entries fighting over one lookup key.
            self._buf.pop(trace.request_id, None)
            self._buf[trace.request_id] = snapshot
            while len(self._buf) > self.size:
                self._buf.popitem(last=False)
            self.recorded += 1

    def get(self, request_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._buf.get(request_id)

    def list(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        """Newest-first summaries (no spans/events — the index view)."""
        with self._lock:
            entries = list(self._buf.values())
        entries.reverse()
        if limit is not None:
            entries = entries[: max(0, int(limit))]
        return [
            {k: v for k, v in e.items()
             if k not in ("spans", "events", "links")}
            | {"n_spans": len(e.get("spans", ())),
               "n_links": len(e.get("links", ()))}
            for e in entries
        ]

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)
