"""Observability subsystem: request-lifecycle tracing, flight recorder,
and on-demand TPU profiling.

Zero-dependency (stdlib only) by design — the trace context is touched on
the serving hot path and from the batch scheduler thread, so it must never
import jax, aiohttp, or prometheus_client. Three pieces:

- ``obs.trace`` — request ID + span API with monotonic timestamps. The
  active trace travels via a ``contextvars.ContextVar`` through the async
  serving path (middleware → cache → breaker → engine submit → executor)
  and by explicit reference through the batch scheduler's admission queue
  (``_Request.trace``), whose worker thread annotates it lock-safely.
- ``obs.recorder`` — ring-buffer flight recorder keeping the full span
  timeline of the last N finished requests (including shed / degraded /
  errored ones), served by ``/debug/requests[/{id}]``.
- ``obs.profiler`` — on-demand ``jax.profiler`` device-trace capture for
  ``POST /debug/profile`` (token-gated), so a TPU trace can be grabbed
  from a live server without restarting it.
- ``obs.ledger`` — the goodput ledger: every device decode step a
  request cost, classified ``delivered | replayed | preempted |
  hedge_loser | wasted_masked | quarantine_burn`` per lane (and per
  hashed tenant behind ``/debug/ledger`` only), with a conservation
  invariant the chaos suite asserts.
- ``obs.slo`` — multi-window (5m/1h) error-budget burn rates for TTFT
  and queue wait per lane, exported as ``slo_*`` gauges and a ``/health``
  section, and consumable by the QoS brownout controller.
- ``obs.steptime`` — the perf-regression sentinel's digests: per-chunk
  step time keyed by (phase, bucket), p50/p95/p99 gauges, trailing
  tok/s per rung, and online breach detection against a boot-loaded
  baseline envelope (``PERF_BASELINES``) or a self-calibrated one.
- ``obs.incidents`` — anomaly-triggered incident capture: a firing
  trigger (step-time breach, burn spike, quarantine/dead-end spike,
  pool exhaustion, breaker open) assembles a bounded evidence bundle
  into a ring behind ``/debug/incidents``, with per-trigger cooldowns.
"""

from .incidents import TRIGGERS, IncidentManager, current_incident_id
from .ledger import (LEDGER_CLASSES, WASTE_CLASSES, GoodputLedger,
                     hash_tenant)
from .recorder import FlightRecorder
from .slo import SLO_QUEUE_WAIT, SLO_TTFT, SloEngine, parse_slo_windows
from .steptime import (PHASE_DECODE, PHASE_PREFILL, PHASE_SPEC_VERIFY,
                       STEP_PHASES, StepTimeSentinel, load_baselines,
                       prefill_bucket)
from .trace import (PHASES, Trace, current_trace, new_request_id,
                    sanitize_request_id, trace_event, use_trace)

__all__ = [
    "PHASES",
    "LEDGER_CLASSES",
    "WASTE_CLASSES",
    "PHASE_DECODE",
    "PHASE_PREFILL",
    "PHASE_SPEC_VERIFY",
    "SLO_QUEUE_WAIT",
    "SLO_TTFT",
    "STEP_PHASES",
    "TRIGGERS",
    "FlightRecorder",
    "GoodputLedger",
    "IncidentManager",
    "SloEngine",
    "StepTimeSentinel",
    "Trace",
    "current_incident_id",
    "current_trace",
    "hash_tenant",
    "load_baselines",
    "new_request_id",
    "parse_slo_windows",
    "prefill_bucket",
    "sanitize_request_id",
    "trace_event",
    "use_trace",
]
