"""Async kubectl execution layer (reference app.py:205-281).

Executes a validated kubectl command as an argv-exec subprocess (never a
shell), with timeout + terminate/kill escalation, structured stdout parsing,
and structured error mapping.

Deliberate fixes over the reference (SURVEY.md §2.3):
- **B2 fixed**: every error path returns a complete ``metadata`` block, so
  the endpoint never KeyErrors into a 500. Timeout / missing-binary /
  bad-command all produce structured ``execution_error`` dicts with
  ``type``/``code``/``message`` (the reference returned bare strings).
- **B6 fixed**: the table parser aligns columns by header character
  positions instead of whitespace-splitting every row, so values containing
  spaces (``NOMINATED NODE``, age like "2d 3h") stay intact; ``-o json``
  output is detected and returned as parsed JSON.
- Timeout escalation: terminate(), 2 s grace (reference app.py:269), then
  kill() — the reference could leak a process that ignored SIGTERM.
"""

from __future__ import annotations

import asyncio
import datetime
import json
import logging
import re
import shlex
import time
from typing import Any, Dict, List, Optional

from ..obs.trace import trace_event

logger = logging.getLogger(__name__)


def utcnow_iso() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


def build_metadata(
    start_iso: str,
    start_ts: float,
    success: bool,
    error_type: Optional[str] = None,
    error_code: Optional[str] = None,
) -> Dict[str, Any]:
    md: Dict[str, Any] = {
        "start_time": start_iso,
        "end_time": utcnow_iso(),
        "duration_ms": (time.monotonic() - start_ts) * 1000.0,
        "success": success,
    }
    if error_type is not None:
        md["error_type"] = error_type
    if error_code is not None:
        md["error_code"] = error_code
    return md


_COLUMN_RE = re.compile(r"\S+(?: \S+)*")  # runs of non-space, single-space joined


def _header_spans(header: str) -> List[tuple]:
    """Column spans from a kubectl table header.

    kubectl separates columns by >=2 spaces (wide columns) or aligns them at
    fixed offsets; single spaces occur *inside* a header name ("NOMINATED
    NODE"). A span runs from its column's start to the next column's start.
    """
    spans = []
    for m in _COLUMN_RE.finditer(header):
        spans.append([m.start(), m.end(), m.group(0)])
    out = []
    for i, (start, _end, name) in enumerate(spans):
        next_start = spans[i + 1][0] if i + 1 < len(spans) else None
        out.append((start, next_start, name))
    return out


def parse_kubectl_stdout(stdout: str) -> Dict[str, Any]:
    """Structure kubectl stdout: JSON → parsed, table → rows, else raw.

    Rebuilt table parser (fixes quirk B6, reference app.py:236-249).
    """
    text = stdout.strip()
    if not text:
        return {"type": "raw", "data": ""}
    if text[0] in "{[":
        try:
            return {"type": "json", "data": json.loads(text)}
        except (json.JSONDecodeError, ValueError):
            pass
    if "\n" not in text:
        return {"type": "raw", "data": text}
    lines = text.splitlines()
    header = lines[0]
    spans = _header_spans(header)
    # Heuristic: a real kubectl table has an ALL-CAPS-ish header with >=2 cols.
    looks_tabular = len(spans) >= 2 and header == header.upper()
    if not looks_tabular:
        return {"type": "raw", "data": text}
    try:
        items = []
        for line in lines[1:]:
            if not line.strip():
                continue
            row: Dict[str, str] = {}
            for start, next_start, name in spans:
                cell = line[start:next_start] if next_start is not None else line[start:]
                row[name.lower()] = cell.strip()
            items.append(row)
        return {"type": "table", "data": items}
    except Exception as parse_err:  # pragma: no cover - defensive, matches app.py:247
        logger.warning("Failed to parse kubectl output: %s", parse_err)
        return {"type": "raw", "data": text}


class CommandExecutor:
    """Executes kubectl commands via asyncio subprocess with a timeout.

    ``kubectl_binary`` is injectable for tests (the reference hardcoded
    ``kubectl``, app.py:213); argv[0] is still re-asserted to be kubectl's
    basename as defense in depth.
    """

    def __init__(self, timeout: float = 30.0, kubectl_binary: str = "kubectl"):
        self.timeout = timeout
        self.kubectl_binary = kubectl_binary

    async def execute(self, command: str) -> Dict[str, Any]:
        start_iso = utcnow_iso()
        start_ts = time.monotonic()
        logger.info("Attempting to execute command: %s", command)
        try:
            args = shlex.split(command)
        except ValueError as ve:
            return {
                "execution_error": {
                    "type": "invalid_command",
                    "code": "parse_error",
                    "message": f"Invalid command format: {ve}",
                },
                "metadata": build_metadata(start_iso, start_ts, False, "invalid_command", "parse_error"),
            }
        if not args or args[0] != "kubectl":
            return {
                "execution_error": {
                    "type": "invalid_command",
                    "code": "not_kubectl",
                    "message": "Command does not start with kubectl",
                },
                "metadata": build_metadata(start_iso, start_ts, False, "invalid_command", "not_kubectl"),
            }
        args[0] = self.kubectl_binary

        trace_event(f"exec: spawning kubectl ({len(args) - 1} args)")
        try:
            process = await asyncio.create_subprocess_exec(
                *args,
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
        except FileNotFoundError:
            logger.error("kubectl binary not found. Is it installed and in PATH?")
            return {
                "execution_error": {
                    "type": "environment_error",
                    "code": "kubectl_not_found",
                    "message": "kubectl command not found",
                },
                "metadata": build_metadata(
                    start_iso, start_ts, False, "environment_error", "kubectl_not_found"
                ),
            }

        try:
            stdout, stderr = await asyncio.wait_for(
                process.communicate(), timeout=self.timeout
            )
        except asyncio.TimeoutError:
            logger.error(
                "Command execution timed out after %ss: %s", self.timeout, command
            )
            trace_event(f"exec: timed out after {self.timeout:g}s; reaping")
            await self._reap(process)
            return {
                "execution_error": {
                    "type": "timeout",
                    "code": "execution_timeout",
                    "message": f"Command execution timed out after {self.timeout:g}s",
                },
                "metadata": build_metadata(start_iso, start_ts, False, "timeout", "execution_timeout"),
            }

        trace_event(f"exec: kubectl exited rc={process.returncode}")
        if process.returncode == 0:
            result_stdout = stdout.decode(errors="replace").strip()
            logger.info("Command executed successfully (%d bytes stdout)", len(result_stdout))
            return {
                "execution_result": parse_kubectl_stdout(result_stdout),
                "metadata": build_metadata(start_iso, start_ts, True),
            }

        result_stderr = stderr.decode(errors="replace").strip()
        code = str(process.returncode)
        logger.error("Command failed with code %s: %s", code, result_stderr)
        return {
            "execution_error": {
                "type": "kubectl_error",
                "code": code,
                "message": result_stderr,
            },
            "metadata": build_metadata(start_iso, start_ts, False, "kubectl_error", code),
        }

    @staticmethod
    async def _reap(process: asyncio.subprocess.Process) -> None:
        """terminate → 2 s grace → kill (reference app.py:267-271, plus the
        missing SIGKILL escalation)."""
        try:
            process.terminate()
        except ProcessLookupError:
            return
        try:
            await asyncio.wait_for(process.wait(), timeout=2)
        except asyncio.TimeoutError:
            try:
                process.kill()
                await process.wait()
            except ProcessLookupError:
                pass
        except Exception as kill_err:  # pragma: no cover
            logger.error("Error terminating timed-out process: %s", kill_err)
