"""HTTP API layer (reference app.py:130-138, 283-400) on aiohttp.

Endpoints (same contract and status codes as the reference):

- ``POST /kubectl-command`` — NL query → validated kubectl command
  (app.py:284-346). 200/401/422(unsafe)/429/500/503/504. Pydantic
  validation errors → 400 (invalid input query). Deliberate choice on quirk
  B1 (SURVEY.md §2.3): generation and execution remain fully separated — the
  hardcoded success-metadata stub is replaced by *real* generation-phase
  metadata, and ``execution_result``/``execution_error`` stay None here.
- ``POST /execute`` — run a validated kubectl command (app.py:356-389).
  200/400(unsafe)/401/429/500; execution errors are structured 200s with
  ``execution_error`` set (B2 fixed in executor.py).
- ``POST /kubectl-command/stream`` — TPU-native addition: streams generated
  tokens as SSE for the multi-turn agent loop (BASELINE config 5).
- ``GET /health`` — readiness-gated (fixes static health, app.py:348-354).
- ``GET /metrics`` — Prometheus (app.py:136-138).

Cross-cutting (middleware): per-IP sliding-window rate limit → 429 with
Retry-After; API-key auth via ``X-API-Key`` (app.py:140-151), disabled when
``API_AUTH_KEY`` unset; HTTP request counters/latency histograms.
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac
import json
import logging
import time
from contextlib import nullcontext, suppress
from typing import Optional

from aiohttp import web
from pydantic import ValidationError

from ..config import ServiceConfig
from ..engine.fallback import FallbackEngine
from ..engine.protocol import (Engine, EngineOverloaded, EngineResult,
                               EngineUnavailable, GenerationTimeout,
                               RequestQuarantined, TenantOverloaded)
from ..engine.qos import classify, use_qos
from ..engine.prompts import render_prompt
from ..obs import (PHASES, FlightRecorder, IncidentManager, Trace,
                   current_trace, new_request_id, sanitize_request_id,
                   use_trace)
from ..obs import profiler as obs_profiler
from .breaker import STATE_CODES, CircuitBreaker
from .cache import CachedSingleFlight
from .executor import CommandExecutor, build_metadata, utcnow_iso
from .metrics import Metrics, WindowedRate
from .output_parser import UnsafeCommandError, parse_llm_output
from .ratelimit import SlidingWindowLimiter, ceil_seconds, client_key
from .sanitize import sanitize_query
from .schemas import (
    CommandResponse,
    EngineMetadata,
    ExecuteRequest,
    ExecutionMetadata,
    HealthResponse,
    Query,
)

logger = logging.getLogger(__name__)

RATE_LIMITED_ROUTES = {"/kubectl-command", "/kubectl-command/stream", "/execute"}
#: /debug/* is matched by prefix in auth_middleware (the flight-recorder
#: lookup route carries a path parameter, so exact-set membership can't
#: cover it).
AUTH_ROUTES = RATE_LIMITED_ROUTES
#: routes the MAX_INFLIGHT_REQUESTS overload gate covers (the ones that
#: occupy the engine).
GENERATE_ROUTES = {"/kubectl-command", "/kubectl-command/stream"}
#: paths the flight recorder skips: LB health probes and Prometheus
#: scrapes arrive several times a second and would flush every real
#: request out of the ring within a minute; recorder lookups recording
#: themselves would do the same.
UNRECORDED_PATHS = ("/health", "/metrics", "/debug/", "/openapi.json", "/docs")


def _retry_after_header(seconds: float) -> dict:
    return {"Retry-After": str(max(1, ceil_seconds(seconds)))}


def _span(name: str, **meta):
    """Span on the active trace, or a no-op when none is active (unit
    tests driving Service methods directly)."""
    trace = current_trace()
    if trace is not None:
        return trace.span(name, **meta)
    return nullcontext()


def _client_key(request: web.Request) -> str:
    """Remote-address key for rate limiting — the leftmost untrusted
    X-Forwarded-For hop when TRUST_PROXY(_HEADERS) is set (behind a
    fronting router tier every request shares one peer IP), the raw peer
    IP otherwise (ratelimit.client_key)."""
    svc: Service = request.app["service"]
    return client_key(request.remote,
                      request.headers.get("X-Forwarded-For"),
                      svc.cfg.trust_proxy_headers)


def _json_error(status: int, detail: str, headers: Optional[dict] = None) -> web.Response:
    return web.json_response({"detail": detail}, status=status, headers=headers or {})


class Service:
    """Bundles the app's long-lived components (the reference kept these as
    module globals, app.py:124-138)."""

    def __init__(self, cfg: ServiceConfig, engine: Engine,
                 executor: Optional[CommandExecutor] = None,
                 metrics: Optional[Metrics] = None):
        self.cfg = cfg
        self.engine = engine
        self.executor = executor or CommandExecutor(timeout=cfg.execution_timeout)
        self.metrics = metrics or Metrics()
        self.cache: CachedSingleFlight[str, str] = CachedSingleFlight(
            cfg.cache_maxsize, cfg.cache_ttl
        )
        self.limiter = SlidingWindowLimiter(cfg.rate_limit_count, cfg.rate_limit_window)
        # Failure containment: a rolling-window breaker around every engine
        # call, an optional rule-based degradation path behind it, and the
        # HTTP-layer inflight counter the overload middleware maintains.
        self.breaker = CircuitBreaker(
            threshold=cfg.breaker_threshold,
            window_secs=cfg.breaker_window_secs,
            recovery_secs=cfg.breaker_recovery_secs,
        )
        self.fallback: Optional[FallbackEngine] = (
            FallbackEngine() if cfg.degraded_fallback else None
        )
        self.inflight_requests = 0
        # Observability: the flight recorder keeps the last N request
        # timelines for /debug/requests; the windowed rate feeds the
        # engine_tokens_per_sec gauge at scrape time (see WindowedRate).
        self.recorder = FlightRecorder(cfg.flight_recorder_size)
        self.token_rate = WindowedRate()
        # Perf-regression sentinel (ISSUE 15): the incident manager
        # watches the engine's cheap health views for firing triggers
        # (step-time breach, burn spike, quarantine/dead-end spike,
        # pool exhaustion, breaker open) and files bounded evidence
        # bundles behind /debug/incidents. The config fingerprint rides
        # every bundle so "what exactly was this server running" is
        # answerable post-hoc (describe() is secret-free by contract).
        self.incidents = IncidentManager(
            ring=cfg.incident_ring,
            cooldown_secs=cfg.incident_cooldown_secs,
            burn_threshold=cfg.incident_burn_threshold,
            thrash_min_blocks=cfg.incident_thrash_min_blocks)
        self.config_fingerprint = hashlib.sha256(
            json.dumps(cfg.describe(), sort_keys=True,
                       default=repr).encode()).hexdigest()[:12]
        # QoS ring (ISSUE 7): the tenant→tier map is parsed once at
        # startup (a typo'd TENANT_TIERS already refused to boot in
        # ServiceConfig.__post_init__); the qos middleware classifies
        # every generation request against it.
        self.tenant_tiers = cfg.tenant_tier_map
        # Inner ring → outer ring: every engine reset-and-replay also
        # counts as a breaker failure, so a flapping engine (reset storm)
        # opens the breaker even while individual requests keep
        # recovering. The supervisor calls from the scheduler thread;
        # marshal onto the event loop when one has been seen (breaker
        # transitions are event-loop-only by design).
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        hook = getattr(engine, "set_reset_listener", None)
        if callable(hook):
            hook(self._on_engine_reset)
        # Zero-downtime weight rollout (ISSUE 13): the controller drives
        # drain → swap → warmup → rejoin → observe → promote-or-rollback
        # over the fleet (or, degenerately, one swap-capable engine).
        # Built against the UNWRAPPED engine — a generate-fault
        # ChaosEngine sits above the fleet facade, and lifecycle calls
        # must reach the real replicas.
        self.rollout = None
        target = getattr(engine, "inner", engine)
        # Capability check reaches the REPLICA engines: a fleet of
        # swap-less engines (ENGINE=fake FLEET_SIZE>1) must 404 the
        # admin surface, not accept a rollout that would drain and
        # eject a healthy replica before discovering the missing seam.
        if hasattr(target, "replicas"):
            swappable = all(
                callable(getattr(rep.engine, "swap_weights", None))
                for rep in target.replicas)
        else:
            swappable = callable(getattr(target, "swap_weights", None))
        if swappable:
            from ..engine.rollout import RolloutController

            self.rollout = RolloutController(
                target,
                canary_share=cfg.rollout_canary_share,
                observe_secs=cfg.rollout_observe_secs,
                burn_gate=cfg.rollout_burn_gate,
                steptime_gate=cfg.rollout_steptime_gate,
                drain_secs=cfg.drain_timeout_secs,
            )

    def _on_engine_reset(self, cause: str) -> None:
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(self.breaker.record_failure)
        else:  # pragma: no cover - pre-traffic reset
            self.breaker.record_failure()

    def retry_after_hint(self) -> float:
        """Retry-After for HTTP-layer sheds: the engine's drain-rate
        estimate when it has one, else a flat second."""
        fn = getattr(self.engine, "retry_after_hint", None)
        if callable(fn):
            try:
                return float(fn())
            except Exception:  # pragma: no cover - defensive
                pass
        return 1.0

    # -------------------------------- perf sentinel / incidents (ISSUE 15)

    def _engine_view(self, name: str) -> Optional[dict]:
        """One cheap engine health view, or None (absent/failing) — the
        incident plane must never take the serving path down."""
        fn = getattr(self.engine, name, None)
        if not callable(fn):
            return None
        try:
            return fn() or None
        except Exception:  # pragma: no cover - defensive
            return None

    def _quarantine_total(self) -> int:
        """Cumulative terminal quarantines across every replica's
        supervisor (cheap attribute reads — never stats(), which drains
        samples owed to the /metrics scrape)."""
        target = getattr(self.engine, "inner", self.engine)
        engines = ([rep.engine for rep in target.replicas]
                   if hasattr(target, "replicas") else [target])
        total = 0
        for eng in engines:
            sup = getattr(eng, "supervisor", None)
            if sup is not None:
                total += sum(getattr(sup, "quarantined", {}).values())
        return total

    def _chunk_rings(self, limit: int = 64) -> dict:
        """Per-replica tails of the scheduler chunk-event rings (the
        /debug/chunks evidence, frozen into the bundle). Deque copies
        retry on concurrent-mutation RuntimeError, same as the route."""
        target = getattr(self.engine, "inner", self.engine)
        engines = ([(str(rep.idx), rep.engine)
                    for rep in target.replicas]
                   if hasattr(target, "replicas")
                   else [("0", target)])
        out = {}
        for key, eng in engines:
            log = getattr(eng, "_chunk_log", None)
            if log is None:
                continue
            events: list = []
            for _ in range(5):
                try:
                    events = list(log)
                    break
                except RuntimeError:
                    continue
            out[key] = events[-limit:]
        return out

    def _incident_bundle(self) -> dict:
        """Assemble one bounded evidence bundle: flight-recorder
        snapshot, chunk rings, and every cheap health section, plus the
        config fingerprint and weights version. Called by the incident
        manager OUTSIDE its lock, at most once per trigger cooldown."""
        return {
            "weights_version": (str(getattr(self.engine,
                                            "weights_version", "") or "")
                                or None),
            "config_fingerprint": self.config_fingerprint,
            "breaker": self.breaker.state,
            "flight_recorder": self.recorder.list(limit=32),
            "chunks": self._chunk_rings(),
            "ledger": self._engine_view("ledger_snapshot"),
            "slo": self._engine_view("slo_health"),
            "qos": self._engine_view("qos_health"),
            "kv_pool": self._engine_view("kv_pool_health"),
            "sharding": self._engine_view("sharding_health"),
            "grammar": self._engine_view("grammar_health"),
            "spec": self._engine_view("spec_health"),
            "fleet": self._engine_view("fleet_health"),
            "steptime": self._engine_view("steptime_health"),
            "rollout": (self.rollout.health()
                        if self.rollout is not None else None),
        }

    def check_incidents(self) -> list:
        """One trigger-evaluation round (the background watcher, the
        /metrics scrape, and /debug/incidents reads all share it —
        cooldowns make redundant evaluation free). Returns NEW bundles."""
        views = {
            "steptime": self._engine_view("steptime_health"),
            "slo": self._engine_view("slo_health"),
            "kv_pool": self._engine_view("kv_pool_health"),
            "grammar": self._engine_view("grammar_health"),
            "breaker": self.breaker.state,
            "quarantined_total": self._quarantine_total(),
        }
        return self.incidents.evaluate(views, self._incident_bundle)

    async def run_engine(self, coro_fn):
        """One engine call under the circuit breaker: fail fast while the
        breaker is open (a half-open probe is the exception), count every
        engine failure, close on success. Overload sheds pass through
        untouched — a full queue is backpressure, not engine brokenness."""
        token = self.breaker.begin()
        if token is None:
            raise EngineUnavailable(
                f"circuit breaker {self.breaker.state}: engine calls "
                "suspended until a half-open probe succeeds"
            )
        # Every exit must either record an outcome or release the probe
        # slot: an overload shed or a client-cancelled call (CancelledError
        # is a BaseException) says nothing about engine health, but if it
        # was the half-open probe, leaving _probe_inflight set would wedge
        # the breaker half-open forever. The token fences stragglers: a
        # call outliving an open transition reports into a dead epoch.
        # Readiness is sampled BEFORE the call: "engine not started"
        # rejections during a restart's warm-up must not open the breaker
        # (it would extend the outage past the model load by up to
        # recovery_secs), while a watchdog trip mid-call — which drops
        # ready AFTER the call began — still counts as the engine failure
        # it is.
        was_ready = bool(getattr(self.engine, "ready", True))
        if self._loop is None:
            self._loop = asyncio.get_running_loop()
        decided = False
        try:
            result = await coro_fn()
        except RequestQuarantined:
            # Terminal per-REQUEST failure: the engine contained it and
            # is healthy — counting it as an engine failure would let one
            # hostile request pattern open the breaker for everyone. The
            # finally below releases the probe slot.
            raise
        except EngineOverloaded:
            # Counted here — once per actual engine shed — rather than in
            # the handlers, where every coalesced single-flight waiter
            # re-raising the shared exception would inflate the counter.
            self.metrics.queue_rejections.labels("engine").inc()
            trace = current_trace()
            if trace is not None:
                trace.shed = True
            raise
        except Exception:
            decided = True
            if was_ready:
                self.breaker.record_failure(token)
            else:
                self.breaker.release_probe(token)
            raise
        else:
            decided = True
            self.breaker.record_success(token)
            return result
        finally:
            if not decided:
                self.breaker.release_probe(token)

    async def degraded_command(self, sanitized_query: str,
                               cause: BaseException) -> tuple[str, EngineResult]:
        """Serve the query from the rule-based FallbackEngine (degraded
        path). Never touches the response cache: a rule-table answer must
        not shadow a real generation after recovery."""
        logger.warning(
            "Serving degraded fallback for query '%s' (breaker=%s): %s",
            sanitized_query, self.breaker.state, cause,
        )
        trace = current_trace()
        if trace is not None:
            trace.degraded = True
            trace.event(f"fallback: engine failed ({cause}); serving "
                        f"rule-based response (breaker={self.breaker.state})")
        with _span("fallback"):
            result = await self.fallback.generate(render_prompt(sanitized_query))
        command = parse_llm_output(result.text)
        self.metrics.degraded_responses.inc()
        # The request DID consult the response cache and miss before the
        # engine failure; count it so hit+miss keeps reconciling with
        # request totals during the outage window.
        self.metrics.cache_misses.inc()
        return command, result

    def cache_key(self, sanitized_query: str) -> str:
        """Response-cache key for one request. Under GRAMMAR_DECODE the
        key is scoped by the request's grammar identity (clamped
        profile + allowed-verbs) — without it, an interactive tenant's
        MUTATING cached command would be served verbatim to a
        readonly-clamped tenant, bypassing the grammar entirely. Off,
        the key is the plain query (pre-ISSUE-11 cache behaviour)."""
        if not self.cfg.grammar_decode:
            return sanitized_query
        from ..constrain import cache_scope, current_grammar
        from ..engine.qos import current_qos

        qctx = current_qos()
        return sanitized_query + cache_scope(
            self.cfg.grammar_profile,
            qctx.lane if qctx is not None else None,
            current_grammar())

    async def generate_command(
        self, sanitized_query: str
    ) -> tuple[str, bool, Optional[EngineResult], bool]:
        """Cache-or-generate; returns (command, from_cache, engine_result,
        degraded). Engine failures (including breaker-open fast-fails)
        degrade to rule-based responses when DEGRADED_FALLBACK is set;
        overload sheds and unsafe outputs always propagate."""
        last_result: list[Optional[EngineResult]] = [None]

        async def supplier() -> str:
            prompt = render_prompt(sanitized_query)
            result = await self.run_engine(lambda: self.engine.generate(
                prompt,
                max_tokens=self.cfg.max_new_tokens,
                temperature=self.cfg.temperature,
                timeout=self.cfg.llm_timeout,
            ))
            last_result[0] = result
            with _span("safety"):
                command = parse_llm_output(result.text)
            logger.info(
                "Engine generated command for query '%s': %s", sanitized_query, command
            )
            return command

        try:
            command, from_cache = await self.cache.get_or_create(
                self.cache_key(sanitized_query), supplier
            )
        except EngineOverloaded:
            raise
        except (EngineUnavailable, GenerationTimeout, asyncio.TimeoutError) as e:
            # Engine-path failure (unavailable / watchdog trip / timeout /
            # open breaker): the degradation target. Anything else — an
            # UnsafeCommandError (422) or a genuine programming bug (500)
            # — propagates; masking a bug as a 200 degraded answer would
            # keep it out of error rates forever (and the stream path
            # already scopes degradation to exactly these exceptions).
            if self.fallback is None:
                raise
            command, result = await self.degraded_command(sanitized_query, e)
            return command, False, result, True
        if from_cache:
            self.metrics.cache_hits.inc()
        else:
            self.metrics.cache_misses.inc()
        return command, from_cache, last_result[0], False


def _finalize_trace(svc: "Service", trace: Trace, status: int,
                    canonical_path: str) -> None:
    """Close out a request's trace: status, phase histograms, recorder.

    Runs for EVERY request — shed 503s, rate-limited 429s, auth 401s and
    unhandled 500s included — which is exactly what makes the flight
    recorder useful during an incident. Probe/scrape/debug paths stay out
    of the recorder (they would flush real traffic from the ring) but
    still feed the HTTP metrics.
    """
    trace.finish(status=status)
    for phase, ms in trace.phase_durations().items():
        # PHASES is a fixed allowlist: label cardinality stays bounded no
        # matter what span names a future code path (or bug) produces.
        if phase in PHASES:
            svc.metrics.request_phase.labels(phase).observe(ms / 1000.0)
    # Unmatched-route 404s stay out too: they bypass the rate limiter
    # (it only covers the serving routes), so an anonymous scanner
    # walking random URLs could otherwise flush every real timeline out
    # of the ring in seconds. They still count in http_requests_total.
    if (canonical_path != "unmatched"
            and not canonical_path.startswith(UNRECORDED_PATHS)):
        svc.recorder.record(trace)


@web.middleware
async def observability_middleware(request: web.Request, handler):
    """Outermost middleware: request-ID minting, trace-context scope, HTTP
    metrics, Server-Timing, and the flight recorder. Wraps the overload/
    ratelimit/auth middlewares so even their rejections carry an
    X-Request-ID and land in the recorder."""
    svc: Service = request.app["service"]
    # Label by the matched route's canonical path, never the raw request
    # path: a scanner walking random 404 URLs would otherwise mint a new
    # Prometheus series per URL and grow /metrics without bound.
    resource = getattr(request.match_info.route, "resource", None)
    path = resource.canonical if resource is not None else "unmatched"
    # Honour a (safe) client-provided X-Request-ID so callers can
    # pre-correlate; mint otherwise. The raw request path goes on the
    # trace (it names ONE request, not a Prometheus series).
    rid = sanitize_request_id(request.headers.get("X-Request-ID")) \
        or new_request_id()
    trace = Trace(rid, request.method, request.path)
    request["trace"] = trace
    status = 500
    try:
        with use_trace(trace):
            response = await handler(request)
        status = response.status
        if not getattr(response, "prepared", False):
            # Headers are still mutable (json_response et al.). Streaming
            # responses sent their headers at prepare() time — the SSE
            # handler stamps X-Request-ID itself before preparing.
            response.headers["X-Request-ID"] = rid
            timing = trace.server_timing()
            if timing:
                response.headers["Server-Timing"] = timing
            # Weight rollout (ISSUE 13): every response echoes the
            # fleet-STABLE checkpoint version; per-replica truth (the
            # canary included) lives in /health's version table.
            ver = getattr(svc.engine, "weights_version", "")
            if ver:
                response.headers.setdefault("X-Model-Version", str(ver))
        return response
    except web.HTTPException as e:
        status = e.status
        e.headers["X-Request-ID"] = rid
        trace.error = type(e).__name__
        raise
    except Exception as e:
        trace.error = f"{type(e).__name__}: {e}"
        raise
    finally:
        elapsed = (time.monotonic() - trace.t0)
        svc.metrics.http_requests.labels(request.method, path, str(status)).inc()
        svc.metrics.http_latency.labels(request.method, path).observe(elapsed)
        _finalize_trace(svc, trace, status, path)


@web.middleware
async def overload_middleware(request: web.Request, handler):
    """HTTP-layer load shedding (MAX_INFLIGHT_REQUESTS): generation routes
    beyond the inflight cap get a fast 503 + Retry-After before any work
    is done — the server stays responsive under a flood instead of
    accumulating handlers that all time out."""
    svc: Service = request.app["service"]
    # <= 0 means unlimited (an operator's -1 must not shed everything).
    cap = svc.cfg.max_inflight_requests
    if cap <= 0 or request.path not in GENERATE_ROUTES:
        return await handler(request)
    if svc.inflight_requests >= cap:
        svc.metrics.queue_rejections.labels("http").inc()
        trace = current_trace()
        if trace is not None:
            trace.shed = True
            trace.event(f"overload: inflight cap reached "
                        f"({svc.inflight_requests}/{cap}); shedding")
        retry = svc.retry_after_hint()
        return _json_error(
            503,
            f"Server overloaded: {svc.inflight_requests} generation "
            f"requests in flight (cap {cap})",
            headers=_retry_after_header(retry),
        )
    svc.inflight_requests += 1
    try:
        return await handler(request)
    finally:
        svc.inflight_requests -= 1


@web.middleware
async def ratelimit_middleware(request: web.Request, handler):
    svc: Service = request.app["service"]
    if request.path in RATE_LIMITED_ROUTES:
        allowed, remaining, retry_after = svc.limiter.check(_client_key(request))
        if not allowed:
            svc.metrics.rate_limited.inc()
            trace = current_trace()
            if trace is not None:
                trace.shed = True
                trace.event("ratelimit: client over quota; rejecting")
            return _json_error(
                429,
                f"Rate limit exceeded: {svc.cfg.rate_limit}",
                headers=svc.limiter.headers(remaining, retry_after),
            )
    return await handler(request)


@web.middleware
async def auth_middleware(request: web.Request, handler):
    """X-API-Key auth (reference app.py:140-151); disabled when no key
    configured."""
    svc: Service = request.app["service"]
    if svc.cfg.auth_enabled and (request.path in AUTH_ROUTES
                                 or request.path.startswith("/debug/")
                                 or request.path.startswith("/admin/")):
        key = request.headers.get("X-API-Key")
        if not key:
            logger.warning("Missing X-API-Key header.")
            return _json_error(401, "Missing X-API-Key header")
        if key != svc.cfg.api_auth_key:
            logger.warning("Invalid API Key received.")
            return _json_error(401, "Invalid API Key")
    return await handler(request)


@web.middleware
async def qos_middleware(request: web.Request, handler):
    """QoS classification (ISSUE 7): every generation request gets a
    tenant key (its API key, else its rate-limit client IP) and a
    priority lane (X-Priority, clamped by the tenant's TENANT_TIERS
    tier), carried to the engine scheduler on a contextvar — the same
    cross-await channel the trace rides. Innermost middleware: only
    authenticated traffic is classified."""
    svc: Service = request.app["service"]
    if request.path not in GENERATE_ROUTES:
        return await handler(request)
    # The API key is the tenant key ONLY when the operator registered it
    # in TENANT_TIERS. A raw header would let a flooder mint a fresh
    # tenant per request (spoofed random keys dodge every per-tenant
    # cap and displace honest tenants as "dominant"), and under
    # single-key auth it would collapse every user into one bucket.
    # Unregistered traffic buckets by client IP — the same identity the
    # rate limiter uses.
    api_key = request.headers.get("X-API-Key")
    if api_key not in svc.tenant_tiers:
        api_key = None
    ctx = classify(
        api_key,
        _client_key(request),
        request.headers.get("X-Priority"),
        svc.tenant_tiers,
        svc.cfg.qos_default_lane,
        # Session identity (ISSUE 20): client-declared, namespaced under
        # the tenant by classify so sessions can't collide (or spend
        # each other's budget) across tenants.
        session=request.headers.get("X-Session-ID"),
    )
    trace = current_trace()
    if trace is not None:
        # The lane is safe to log; the tenant key may be an API key —
        # the trace records only which kind keyed it.
        trace.event(f"qos: lane={ctx.lane} "
                    f"(tenant={'tier-key' if api_key else 'client-ip'})")
    # Grammar intent (ISSUE 11): a request may LOWER itself to the
    # read-only grammar (X-Grammar-Profile) and/or narrow the verb set
    # (X-Allowed-Verbs, comma-separated) — validated HERE, at
    # admission: unknown verbs and verbs outside the request's clamped
    # profile are a 400, not a silent widening. Headers on a
    # GRAMMAR_DECODE=false deployment are a 400 too — a restriction
    # the engine cannot enforce must not be silently dropped.
    g_profile = request.headers.get("X-Grammar-Profile")
    g_verbs = request.headers.get("X-Allowed-Verbs")
    gctx = None
    if g_profile is not None or g_verbs is not None:
        from ..constrain import GrammarContext, validate_restriction

        if not svc.cfg.grammar_decode:
            return _json_error(
                400, "grammar restrictions require GRAMMAR_DECODE=true")
        verbs = None
        if g_verbs is not None:
            verbs = frozenset(
                v.strip().lower() for v in g_verbs.split(",")
                if v.strip())
        gctx = GrammarContext(
            profile=(g_profile or "").strip().lower() or None,
            allowed_verbs=verbs)
        # ONE validation rule, shared with the engine runtime
        # (constrain.validate_restriction): unknown profile, verbs
        # outside the request's CLAMPED profile, or any verb
        # restriction under the unenforceable permissive A/B profile —
        # all refused here, at admission, never silently dropped.
        err = validate_restriction(svc.cfg.grammar_profile, ctx.lane,
                                   gctx)
        if err is not None:
            return _json_error(400, err)
        if trace is not None:
            trace.event(
                f"grammar: request profile={gctx.profile or 'base'}"
                + (f", {len(verbs)} allowed verbs" if verbs else ""))
    with use_qos(ctx):
        if gctx is not None:
            from ..constrain import use_grammar

            with use_grammar(gctx):
                return await handler(request)
        return await handler(request)


def _record_engine_spans(trace: Optional[Trace], t_block0: float,
                         t_block1: float, er: EngineResult) -> None:
    """Reconstruct the engine block's phase timeline onto the trace.

    The engine call is one awaited block from the handler's view; the
    EngineResult carries where that time went (queue_ms / prefill_ms /
    decode_ms as the engine measured them). They are laid back-to-back
    from the block's start, and whatever the three phases don't account
    for — detokenization, event-loop handoff, chunk-pipeline slack — is
    the ``detokenize`` remainder, so the span durations always sum to the
    block's wall time (the property the /debug/requests timeline is
    documented to hold). The separately-measured host detok time rides
    along as span metadata when the engine reports it.
    """
    if trace is None:
        return
    k = 1000.0
    t_q = t_block0 + er.queue_ms / k
    t_p = t_q + er.prefill_ms / k
    t_d = t_p + er.decode_ms / k
    # Clamp into the block: the engine's own spans can overrun the
    # handler-observed wall time by scheduler jitter; never let a span
    # escape the block it happened in.
    t_q, t_p, t_d = (min(t, t_block1) for t in (t_q, t_p, t_d))
    trace.add_span("queue_wait", t_block0, t_q)
    trace.add_span("prefill", t_q, t_p)
    trace.add_span("decode", t_p, t_d)
    meta = {"detok_host_ms": round(er.detok_ms, 3)} if er.detok_ms else {}
    trace.add_span("detokenize", t_d, t_block1, **meta)


async def handle_kubectl_command(request: web.Request) -> web.Response:
    """POST /kubectl-command (reference app.py:284-346)."""
    svc: Service = request.app["service"]
    trace: Optional[Trace] = request.get("trace")
    start_iso = utcnow_iso()
    t0 = time.monotonic()
    try:
        with _span("validate"):
            q = Query.model_validate(await request.json())
    except (ValidationError, ValueError) as e:
        return _json_error(400, f"Invalid input query: {e}")

    logger.info("Received query: '%s'", q.query)
    sanitized_query = sanitize_query(q.query)
    if len(sanitized_query) < 3:
        return _json_error(400, "Invalid input query: too short after sanitation")

    t_block0 = time.monotonic()
    try:
        command, from_cache, engine_result, degraded = await svc.generate_command(
            sanitized_query
        )
    except TenantOverloaded as e:
        # 429, not 503: the per-TENANT cap tripped — the flooding tenant
        # backs off while everyone else keeps being served; Retry-After
        # is priced from the shed lane's own drain rate.
        return _json_error(429, f"Tenant over queue quota: {e}",
                           headers=_retry_after_header(e.retry_after))
    except EngineOverloaded as e:
        return _json_error(503, f"Server overloaded: {e}",
                           headers=_retry_after_header(e.retry_after))
    except RequestQuarantined as e:
        # 410 Gone: the request itself poisoned decode steps past its
        # quarantine retry budget. Terminal by design — a retry would
        # just poison another batch, so no Retry-After and no fallback.
        logger.error("Request quarantined for query '%s': %s",
                     sanitized_query, e)
        return _json_error(410, f"Request quarantined: {e}")
    except EngineUnavailable as e:
        headers = None
        if svc.rollout is not None and svc.rollout.active:
            # Weight rollout (ISSUE 13): while a swap holds the only
            # capacity (the FLEET_SIZE=1 in-place swap runs WITHOUT a
            # fleet facade to price the shed), tell the LB when to
            # re-offer instead of returning a bare 503.
            hint = float(getattr(svc.engine, "swap_hint", 0.0) or 0.0)
            headers = _retry_after_header(
                hint or max(2.0, svc.rollout.drain_secs / 2.0))
        return _json_error(503, f"Engine not available: {e}",
                           headers=headers)
    except (GenerationTimeout, asyncio.TimeoutError):
        logger.error("Engine timed out after %ss for query: %s", svc.cfg.llm_timeout, sanitized_query)
        return _json_error(504, "LLM request timed out")
    except UnsafeCommandError as e:
        logger.error("Engine generated unsafe command: %s", e)
        svc.metrics.unsafe_commands.labels("llm").inc()
        return _json_error(422, f"LLM generated unsafe command: {e}")
    except Exception as e:
        logger.exception("Unexpected error processing query '%s'", sanitized_query)
        return _json_error(500, "Internal server error processing request")

    t_block1 = time.monotonic()
    duration_ms = (t_block1 - t0) * 1000.0
    engine_md = None
    if engine_result is not None:
        # Degraded rule-table responses stay out of the engine latency /
        # throughput series: their ~0 ms TTFT and 10^5 tok/s would paint
        # record-best dashboards during the exact outage the breaker
        # metrics are surfacing (degraded_responses_total tracks them).
        if not degraded:
            svc.metrics.ttft.observe(engine_result.ttft_ms / 1000.0)
            svc.metrics.gen_latency.observe(duration_ms / 1000.0)
            svc.metrics.tokens_generated.inc(max(engine_result.completion_tokens, 0))
            # Feeds the windowed engine_tokens_per_sec gauge (read at
            # scrape time) — the old per-request .set() only ever showed
            # the LAST finisher and was racy under concurrent decode.
            svc.token_rate.add(engine_result.completion_tokens)
            if engine_result.prefix_cache_hit:
                svc.metrics.prefix_cache_hits.inc()
            # Non-degraded engine block: lay queue/prefill/decode/detok
            # spans over it from the engine's own measurements. A degraded
            # block already carries its "fallback" span (plus the failure
            # event), and a cache hit its "cache" span below.
            if not from_cache:
                _record_engine_spans(trace, t_block0, t_block1, engine_result)
        engine_md = EngineMetadata(
            queue_ms=engine_result.queue_ms,
            prefill_ms=engine_result.prefill_ms,
            decode_ms=engine_result.decode_ms,
            detok_ms=engine_result.detok_ms,
            ttft_ms=engine_result.ttft_ms,
            prompt_tokens=engine_result.prompt_tokens,
            completion_tokens=engine_result.completion_tokens,
            tokens_per_sec=engine_result.tokens_per_sec,
            prefix_cache_hit=engine_result.prefix_cache_hit,
            engine=engine_result.engine,
        )
    if trace is not None:
        trace.from_cache = from_cache
        if from_cache:
            trace.add_span("cache", t_block0, t_block1)
    with _span("respond"):
        timings = trace.phase_durations() if trace is not None else None
        body = CommandResponse(
            kubectl_command=command,
            execution_result=None,   # generation and execution are separate (B1, deliberate)
            execution_error=None,
            from_cache=from_cache,
            metadata=ExecutionMetadata(**build_metadata(start_iso, t0, True)),
            engine_metadata=engine_md,
            # Degraded is rule-table fallback OR an engine-side
            # starvation truncation (ISSUE 20) — either way the client
            # must not take the answer as full-fidelity.
            degraded=degraded or (engine_result is not None
                                  and engine_result.degraded),
            timings=timings,
        )
        payload = body.model_dump()
    return web.json_response(payload)


async def handle_kubectl_command_stream(request: web.Request) -> web.StreamResponse:
    """POST /kubectl-command/stream — SSE token stream (TPU-native addition
    for the agent loop, BASELINE config 5)."""
    svc: Service = request.app["service"]
    try:
        q = Query.model_validate(await request.json())
    except (ValidationError, ValueError) as e:
        return _json_error(400, f"Invalid input query: {e}")
    sanitized_query = sanitize_query(q.query)
    if len(sanitized_query) < 3:
        return _json_error(400, "Invalid input query: too short after sanitation")

    trace: Optional[Trace] = request.get("trace")
    resp = web.StreamResponse(
        status=200,
        headers={"Content-Type": "text/event-stream", "Cache-Control": "no-cache"},
    )
    if trace is not None:
        # Streaming commits headers at prepare() time, before any phase
        # has run — the middleware can't stamp them afterwards. The ID is
        # known now; Server-Timing (whose values aren't) stays JSON-only.
        resp.headers["X-Request-ID"] = trace.request_id
    # Weight rollout (ISSUE 13): the stream commits to the fleet-stable
    # version before the first byte; version pinning (engine/fleet.py)
    # then guarantees an established stream never silently crosses onto
    # other weights mid-flight.
    _ver = getattr(svc.engine, "weights_version", "")
    if _ver:
        resp.headers["X-Model-Version"] = str(_ver)
    await resp.prepare(request)

    def sse(payload: str, event: Optional[str] = None) -> bytes:
        # SSE framing: every payload line needs its own "data:" field —
        # naive interpolation would corrupt multi-line token pieces.
        lines = payload.split("\n") or [""]
        frame = (f"event: {event}\n" if event else "") + "".join(
            f"data: {line}\n" for line in lines
        ) + "\n"
        return frame.encode()

    # Everything goes through the SAME cache + single-flight as the
    # non-streaming endpoint (fixes the half-applied B4: concurrent
    # identical streams no longer each run a full generation). The flight
    # initiator streams tokens live; cache hits and coalesced waiters —
    # streaming or not — replay the final command as one event. As with
    # the non-streaming path, a disconnecting client does not cancel the
    # shared generation: it completes and fills the cache (the documented
    # SingleFlight semantics).
    write_ok = True

    async def write_safe(frame: bytes) -> None:
        nonlocal write_ok
        if not write_ok:
            return
        try:
            await resp.write(frame)
        except Exception:
            write_ok = False  # client went away mid-stream; stop writing

    # The supplier never touches the socket — it hands tokens to this
    # handler through a queue, and the handler writes them. A slow-reading
    # client therefore stalls only its own drain loop, never the shared
    # flight the coalesced waiters are blocked on.
    _DONE = object()
    token_q: asyncio.Queue = asyncio.Queue()

    async def supplier() -> str:
        async def run() -> str:
            pieces: list[str] = []
            stream = svc.engine.generate_stream(
                render_prompt(sanitized_query),
                max_tokens=svc.cfg.max_new_tokens,
                temperature=svc.cfg.temperature,
                timeout=svc.cfg.llm_timeout,
            )
            async for piece in stream:
                pieces.append(piece)
                token_q.put_nowait(piece)
            return "".join(pieces)

        try:
            # Same breaker accounting as the non-streaming path; parsing
            # stays outside so an unsafe output doesn't count as an
            # engine failure.
            text = await svc.run_engine(run)
            with _span("safety"):
                return parse_llm_output(text)
        finally:
            token_q.put_nowait(_DONE)

    try:
        flight = asyncio.ensure_future(
            svc.cache.get_or_create(svc.cache_key(sanitized_query),
                                    supplier)
        )
        # Drain live tokens while the flight runs. Only our own supplier
        # fills token_q; a cache hit or a coalesced flight leaves it empty
        # and we just wait for the flight's result.
        getter: Optional[asyncio.Future] = None
        try:
            while True:
                getter = asyncio.ensure_future(token_q.get())
                await asyncio.wait({getter, flight},
                                   return_when=asyncio.FIRST_COMPLETED)
                if getter.done():
                    piece = getter.result()
                    if piece is _DONE:
                        break
                    await write_safe(sse(piece))
                else:
                    break  # flight finished without our supplier running
        finally:
            if getter is not None and not getter.done():
                getter.cancel()
        command, from_cache = await flight
        if trace is not None:
            trace.from_cache = from_cache
        if from_cache:
            # A cache hit or another request's in-flight generation served
            # us; our supplier never streamed — replay the result.
            svc.metrics.cache_hits.inc()
            await write_safe(sse(command))
        else:
            svc.metrics.cache_misses.inc()
        await write_safe(sse(command, event="done"))
    except UnsafeCommandError as e:
        svc.metrics.unsafe_commands.labels("llm").inc()
        await write_safe(sse(str(e), event="error"))
    except TenantOverloaded as e:
        # In-band 429 analog: THIS tenant is over its queue quota.
        await write_safe(sse(f"tenant over queue quota: {e}",
                             event="error"))
    except EngineOverloaded as e:
        # Shedding stays an error even with the fallback enabled: the
        # client should back off, not be absorbed by the rule table.
        # (queue_rejections is counted inside run_engine, once per shed.)
        await write_safe(sse(f"engine overloaded: {e}", event="error"))
    except RequestQuarantined as e:
        # Terminal: this request poisons decode steps; never degraded,
        # never retried (410 analog for an already-committed stream).
        await write_safe(sse(f"request quarantined: {e}", event="error"))
    except (EngineUnavailable, GenerationTimeout, asyncio.TimeoutError) as e:
        if svc.fallback is not None:
            try:
                command, _result = await svc.degraded_command(
                    sanitized_query, e)
            except UnsafeCommandError as ue:
                # A rule template interpolated a query capture the safety
                # validator rejects ("logs of web;id") — same in-band 422
                # analog as the primary-path unsafe case.
                svc.metrics.unsafe_commands.labels("llm").inc()
                await write_safe(sse(str(ue), event="error"))
            else:
                # A "degraded" frame before "done" so agent loops that
                # only watch "done" keep working while aware clients can
                # tell.
                await write_safe(sse(command, event="degraded"))
                await write_safe(sse(command, event="done"))
        elif isinstance(e, EngineUnavailable):
            await write_safe(sse(f"engine unavailable: {e}", event="error"))
        else:
            await write_safe(sse("LLM request timed out", event="error"))
    except Exception:
        # The 200 status is already on the wire; the best we can do is a
        # structured error event rather than a silently truncated stream.
        logger.exception("Stream generation failed for query '%s'", sanitized_query)
        await write_safe(sse("internal error during generation", event="error"))
    try:
        await resp.write_eof()
    except Exception:
        pass  # client already gone; the stream is finished either way
    return resp


async def handle_execute(request: web.Request) -> web.Response:
    """POST /execute (reference app.py:356-389)."""
    svc: Service = request.app["service"]
    trace: Optional[Trace] = request.get("trace")
    try:
        with _span("validate"):
            req = ExecuteRequest.model_validate(await request.json())
    except (ValidationError, ValueError) as e:
        return _json_error(400, f"Invalid request: {e}")

    logger.info("Received execute request for command: '%s'", req.execute)
    from .safety import unsafe_reason

    with _span("safety"):
        reason = unsafe_reason(req.execute)
    if reason is not None:
        svc.metrics.unsafe_commands.labels("user").inc()
        return _json_error(400, f"Command failed safety checks: {reason}")

    with _span("execute"):
        execution_data = await svc.executor.execute(req.execute)
    outcome = "success" if execution_data["metadata"]["success"] else (
        execution_data["metadata"].get("error_type") or "error"
    )
    svc.metrics.executions.labels(outcome).inc()

    with _span("respond"):
        body = CommandResponse(
            kubectl_command=req.execute,
            execution_result=execution_data.get("execution_result"),
            execution_error=execution_data.get("execution_error"),
            from_cache=False,
            metadata=ExecutionMetadata(**execution_data["metadata"]),
            timings=trace.phase_durations() if trace is not None else None,
        )
        payload = body.model_dump()
    return web.json_response(payload)


def _device_count(app: web.Application) -> int:
    """Device count, enumerated once and cached on the app: LBs probe
    /health several times a second and re-importing jax + listing devices
    per probe is measurable work for an answer that never changes."""
    devices = app.get("_device_count")
    if devices is None:
        try:
            import jax

            devices = len(jax.devices())
        except Exception:
            return 0   # transient failure: don't cache; retry next probe
        app["_device_count"] = devices
    return devices


async def handle_health(request: web.Request) -> web.Response:
    """GET /health — readiness-gated (SURVEY.md §3.3), with the breaker's
    state surfaced so operators can tell "engine down" from "engine up but
    circuit open / serving fallback"."""
    svc: Service = request.app["service"]
    ready = bool(getattr(svc.engine, "ready", False))
    breaker = svc.breaker.state
    # Inner-ring containment state: when the engine last reset its
    # decode state and why — read off the supervisor directly (NOT via
    # engine.stats(), which drains the fetch-latency samples owed to the
    # /metrics histogram; LBs probe /health several times a second).
    last_reset = last_cause = None
    sup = (getattr(svc.engine, "supervisor", None)
           or getattr(getattr(svc.engine, "inner", None), "supervisor",
                      None))
    if sup is not None and sup.last_reset_wall:
        last_reset = (time.strftime("%Y-%m-%dT%H:%M:%S",
                                    time.gmtime(sup.last_reset_wall)) + "Z")
        last_cause = sup.last_reset_cause
    # Fleet deployments (engine/fleet.py): a per-replica section — state,
    # breaker, occupancy, last reset/cause — plus the fleet rollup
    # (migration/hedge/drain counters). The cheap health view never calls
    # stats() (that drains samples owed to the /metrics scrape). The
    # fleet's most-recent reset also backfills the top-level fields.
    fleet = None
    fh = getattr(svc.engine, "fleet_health", None)
    if callable(fh):
        fleet = fh() or None
    if fleet is not None and last_reset is None:
        last_reset = fleet.get("last_reset")
        last_cause = fleet.get("last_reset_cause")
    # QoS ring (ISSUE 7): per-lane queue depth, brownout level/shares,
    # and preemptions in the last minute — the cheap view (qos_health
    # never calls stats(), same rule as the fleet section).
    qos = None
    qh = getattr(svc.engine, "qos_health", None)
    if callable(qh):
        qos = qh() or None
    # SLO burn rates (ISSUE 8): multi-window error-budget view — cheap
    # (a bounded-deque scan, never stats()), same rule as qos/fleet.
    slo = None
    sh = getattr(svc.engine, "slo_health", None)
    if callable(sh):
        slo = sh() or None
    # KV pool (ISSUE 10): block-state counts + radix hit rates — cheap
    # (host counters, never stats()), same rule as qos/fleet/slo.
    kv_pool = None
    kph = getattr(svc.engine, "kv_pool_health", None)
    if callable(kph):
        kv_pool = kph() or None
    # Sharding (ISSUE 14): mesh shape, residual TP fraction, pool-
    # sharded + mesh-fallback flags — cheap host attributes, same rule.
    sharding = None
    shh = getattr(svc.engine, "sharding_health", None)
    if callable(shh):
        sharding = shh() or None
    # Grammar (ISSUE 11): compiled-grammar hash, state count, forced/
    # masked totals — cheap host counters, same rule as the rest.
    grammar = None
    gh = getattr(svc.engine, "grammar_health", None)
    if callable(gh):
        grammar = gh() or None
    # Speculative decoding (ISSUE 12): draft model id, k, acceptance
    # rate, degradation state — cheap host counters, same rule.
    spec = None
    sph = getattr(svc.engine, "spec_health", None)
    if callable(sph):
        spec = sph() or None
    # Weight rollout (ISSUE 13): state machine position, target/stable
    # versions, the per-replica version table, rollbacks by cause —
    # cheap controller counters, same rule as the rest. The fleet
    # section above carries each replica's weights_version too.
    rollout = svc.rollout.health() if svc.rollout is not None else None
    # Perf-regression sentinel (ISSUE 15): step-time digest summary +
    # breach state (cheap bounded-ring reads), and the incident ring's
    # captured/suppressed totals.
    steptime = None
    sth = getattr(svc.engine, "steptime_health", None)
    if callable(sth):
        steptime = sth() or None
    incidents = svc.incidents.snapshot()
    body = HealthResponse(
        status="healthy" if ready and breaker == "closed" else "degraded",
        engine=getattr(svc.engine, "name", "unknown"),
        engine_ready=ready,
        model=svc.cfg.model_name,
        devices=_device_count(request.app),
        breaker=breaker,
        degraded_fallback=svc.fallback is not None,
        last_reset=last_reset,
        last_reset_cause=last_cause,
        fleet=fleet,
        qos=qos,
        slo=slo,
        kv_pool=kv_pool,
        sharding=sharding,
        grammar=grammar,
        spec=spec,
        rollout=rollout,
        steptime=steptime,
        incidents=incidents,
    )
    # The HTTP status tracks engine readiness alone: an open breaker with
    # the engine process alive still serves (fallback and/or cache), and
    # half-open probes need traffic to ever re-close it. A 503 carries
    # Retry-After priced from the FLEET-wide drain rate (the engine's
    # aggregate hint) so draining instances tell LBs when to re-probe.
    if ready:
        return web.json_response(body.model_dump(), status=200)
    return web.json_response(
        body.model_dump(), status=503,
        headers=_retry_after_header(svc.retry_after_hint()))


def _debug_forbidden(request: web.Request) -> Optional[web.Response]:
    """Token gate for /debug/*: when DEBUG_TOKEN is configured, require a
    matching X-Debug-Token header ON TOP of the API-key auth middleware.
    Debug surfaces (request timelines, profiler captures) are
    operator-facing — a leaked client API key must not open them."""
    token = request.app["service"].cfg.debug_token
    if not token:
        return None
    supplied = request.headers.get("X-Debug-Token", "")
    # Compare bytes: compare_digest on str raises TypeError for
    # non-ASCII input, and header values may legally carry 0x80-0xFF —
    # a garbage token must 403, not 500.
    if not hmac.compare_digest(
            supplied.encode("utf-8", "surrogateescape"), token.encode()):
        return _json_error(403, "Invalid or missing X-Debug-Token")
    return None


async def handle_debug_profile(request: web.Request) -> web.Response:
    """POST /debug/profile?seconds=N — capture a jax.profiler device trace
    while live traffic runs (SURVEY.md §5 tracing row; TensorBoard-
    loadable). Auth- and token-gated; one capture at a time; only the
    newest few captures are retained (obs/profiler.py). ``/debug/trace``
    is the pre-rename alias."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    try:
        seconds = obs_profiler.clamp_seconds(
            float(request.query.get("seconds", 2.0)))
    except ValueError:
        return _json_error(400, "seconds must be a number")
    if request.app.get("_tracing"):
        return _json_error(409, "a trace is already in progress")
    request.app["_tracing"] = True
    try:
        result = await obs_profiler.capture(seconds)
    except Exception as e:  # pragma: no cover - backend-dependent
        logger.exception("trace capture failed")
        return _json_error(500, f"trace capture failed: {e}")
    finally:
        request.app["_tracing"] = False
    return web.json_response(result)


async def handle_debug_requests(request: web.Request) -> web.Response:
    """GET /debug/requests — newest-first flight-recorder index (summaries
    only; fetch a request_id's full timeline from the detail route)."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    try:
        limit = int(request.query.get("limit", 50))
    except ValueError:
        return _json_error(400, "limit must be an integer")
    return web.json_response({
        "size": svc.recorder.size,
        "recorded": svc.recorder.recorded,
        "requests": svc.recorder.list(limit=limit),
    })


async def handle_debug_request_detail(request: web.Request) -> web.Response:
    """GET /debug/requests/{id} — one request's full span timeline."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    rid = request.match_info["id"]
    entry = svc.recorder.get(rid)
    if entry is None:
        return _json_error(
            404,
            f"request {rid!r} not in the flight recorder (keeps the last "
            f"{svc.recorder.size}; is FLIGHT_RECORDER_SIZE large enough?)",
        )
    return web.json_response(entry)


async def handle_debug_chunks(request: web.Request) -> web.Response:
    """GET /debug/chunks — the decode pipeline's flight record: the last
    N chunk dispatch/consume/prune events (timestamps, KV bucket, device
    n_alive, fetch latency) straight off the scheduler's ring buffer,
    plus the live pipeline stats. The chunk-granular companion to
    /debug/requests when 'serving is slower than the device' needs a
    timeline, not a counter."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    try:
        limit = int(request.query.get("limit", 100))
    except ValueError:
        return _json_error(400, "limit must be an integer")
    # The scheduler thread appends to the ring while we copy; CPython
    # raises "deque mutated during iteration" rather than corrupting, so
    # retry the snapshot a few times instead of 500ing the one endpoint
    # meant for debugging a busy pipeline.
    log = getattr(svc.engine, "_chunk_log", ())
    events: list = []
    for _ in range(5):
        try:
            events = list(log)
            break
        except RuntimeError:
            continue
    stats_fn = getattr(svc.engine, "stats", None)
    stats = stats_fn() if callable(stats_fn) else {}
    if stats:
        # stats() drains the fetch-latency samples; forward them to the
        # histogram rather than dropping them on the floor.
        svc.metrics.observe_pipeline(stats)
    keys = ("pipe_depth", "pipe_inflight", "device_active_slots",
            "device_termination", "wasted_decode_steps",
            "chunks_dispatched", "chunks_consumed", "chunks_pruned")
    return web.json_response({
        "events": events[-limit:] if limit > 0 else [],
        "pipeline": {k: stats[k] for k in keys if k in stats},
    })


async def handle_debug_ledger(request: web.Request) -> web.Response:
    """GET /debug/ledger — the goodput ledger (obs/ledger.py): every
    device decode step classified delivered vs the waste classes, per
    lane AND per (hashed) tenant, with the conservation check. The
    tenant breakdown lives here and only here — tenants must never
    become metric labels (cardinality), and the keys are sha256 hashes
    (they may be API keys), the same form LOG_FORMAT=json stamps on log
    lines so the two surfaces join."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    fn = getattr(svc.engine, "ledger_snapshot", None)
    snap = fn() if callable(fn) else None
    if not snap:   # absent, or a wrapper forwarding to an engine without one
        return _json_error(
            404, "engine exposes no goodput ledger (telemetry plane is "
                 "wired into the chunked schedulers and the fleet)")
    return web.json_response(snap)


async def _attach_incident_profiles(app: web.Application, svc: Service,
                                    bundles: list) -> None:
    """Optionally attach a rate-limited jax.profiler capture to fresh
    bundles (INCIDENT_PROFILE_SECS > 0, jax engines only). Serialized
    against operator-requested captures via the same _tracing flag, and
    bounded by the trigger cooldowns that bounded the bundles."""
    secs = svc.cfg.incident_profile_secs
    if secs <= 0 or not bundles:
        return
    import sys

    if "jax" not in sys.modules:
        return   # fake/openai deployment: nothing to profile
    if app.get("_tracing"):
        bundles[0]["profile"] = {"skipped": "capture already running"}
        return
    app["_tracing"] = True
    try:
        result = await obs_profiler.capture(secs)
        bundles[0]["profile"] = result
    except Exception as e:  # pragma: no cover - backend-dependent
        bundles[0]["profile"] = {"error": str(e)}
    finally:
        app["_tracing"] = False


async def handle_debug_incidents(request: web.Request) -> web.Response:
    """GET /debug/incidents — the incident ring's newest-first index
    (ISSUE 15). Each entry is a bounded evidence bundle an anomaly
    trigger assembled automatically (step-time breach, SLO burn spike,
    quarantine/dead-end spike, pool exhaustion, breaker open); fetch a
    full bundle from /debug/incidents/{id}. Reading runs one trigger
    evaluation first, so a freshly-tripped sentinel files its bundle on
    the very request that comes looking for it."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    try:
        new = svc.check_incidents()
        await _attach_incident_profiles(request.app, svc, new)
    except Exception:   # pragma: no cover - defensive
        logger.exception("incident evaluation failed")
    return web.json_response({
        **svc.incidents.snapshot(),
        "incidents": svc.incidents.list(),
    })


async def handle_debug_incident_detail(request: web.Request
                                       ) -> web.Response:
    """GET /debug/incidents/{id} — one incident's full evidence bundle
    (flight recorder, chunk rings, ledger/SLO/pool/spec health
    snapshots, config fingerprint, weights version)."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    iid = request.match_info["id"]
    bundle = svc.incidents.get(iid)
    if bundle is None:
        return _json_error(
            404,
            f"incident {iid!r} not in the ring (keeps the newest "
            f"{svc.incidents.ring_size}; is INCIDENT_RING large "
            f"enough?)")
    return web.json_response(bundle)


def _rollout_unavailable(svc: Service) -> Optional[web.Response]:
    if svc.rollout is None:
        return _json_error(
            404, "engine has no weight-rollout support (rollouts are "
                 "wired into the fleet and the swap-capable engines)")
    return None


async def handle_admin_rollout_post(request: web.Request) -> web.Response:
    """POST /admin/rollout {"checkpoint": path} — begin a zero-downtime
    weight rollout (ISSUE 13): drain one canary replica, swap it to the
    versioned checkpoint, observe it under a bounded traffic share, then
    promote the rest or roll back automatically. Token-gated like the
    debug surfaces — weight changes are operator actions."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    unavailable = _rollout_unavailable(svc)
    if unavailable is not None:
        return unavailable
    try:
        body = await request.json()
    except Exception:
        return _json_error(400, "body must be JSON")
    checkpoint = (body or {}).get("checkpoint")
    if not isinstance(checkpoint, str) or not checkpoint.strip():
        return _json_error(400, "body needs a 'checkpoint' path string")
    from ..engine.rollout import RolloutError

    try:
        status = await svc.rollout.start_rollout(checkpoint.strip())
    except RolloutError as e:
        return _json_error(409, str(e))
    return web.json_response(status, status=202)


async def handle_admin_rollout_get(request: web.Request) -> web.Response:
    """GET /admin/rollout — the rollout state machine's full status:
    state, target/stable versions, canary + share, gate verdicts, the
    drain→swap→rejoin→promote timeline, and rollback history."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    unavailable = _rollout_unavailable(svc)
    if unavailable is not None:
        return unavailable
    return web.json_response(svc.rollout.status())


async def handle_admin_rollout_abort(request: web.Request) -> web.Response:
    """POST /admin/rollout/abort — roll the in-flight rollout back
    (cause ``aborted``); 409 when nothing is in flight."""
    denied = _debug_forbidden(request)
    if denied is not None:
        return denied
    svc: Service = request.app["service"]
    unavailable = _rollout_unavailable(svc)
    if unavailable is not None:
        return unavailable
    from ..engine.rollout import RolloutError

    try:
        status = await svc.rollout.abort()
    except RolloutError as e:
        return _json_error(409, str(e))
    return web.json_response(status)


async def handle_metrics(request: web.Request) -> web.Response:
    svc: Service = request.app["service"]
    # Engine gauges are sampled at scrape time (live scheduler state, not a
    # push path the hot loop has to touch).
    stats_fn = getattr(svc.engine, "stats", None)
    stats = {}
    if callable(stats_fn):
        stats = stats_fn()
        svc.metrics.batch_occupancy.set(stats.get("batch_occupancy", 0))
        svc.metrics.queue_depth.set(stats.get("queue_depth", 0))
        svc.metrics.kv_pool_used.set(stats.get("kv_pages_used", 0))
        svc.metrics.kv_pool_total.set(stats.get("kv_pages_total", 0))
        # Decode-pipeline metrics (pipe occupancy, wasted decode steps,
        # chunk dispatch/consume/prune counts, fetch-latency histogram).
        svc.metrics.observe_pipeline(stats)
        # Containment counters (resets, quarantines, health trips,
        # replayed tokens) — same delta-mirror pattern.
        svc.metrics.observe_containment(stats)
        # Fleet section (engine/fleet.py): per-replica gauges +
        # migration/hedge/drain/eject counters.
        if stats.get("fleet"):
            svc.metrics.observe_fleet(stats["fleet"])
        # QoS section (engine/qos.py): per-lane depth/occupancy gauges +
        # preemption/expiry/displacement counters + brownout level.
        if stats.get("qos"):
            svc.metrics.observe_qos(stats["qos"])
        # Telemetry plane (ISSUE 8): goodput ledger lane table +
        # SLO burn-rate gauges — same delta-mirror pattern.
        if stats.get("ledger"):
            svc.metrics.observe_ledger(stats["ledger"])
        if stats.get("slo"):
            svc.metrics.observe_slo(stats["slo"])
        # KV pool + radix sharing (ISSUE 10): block-state gauges +
        # sharing/COW/radix-hit counters — same delta-mirror pattern.
        if stats.get("kv_pool"):
            svc.metrics.observe_kv_pool(stats["kv_pool"])
        # Tensor-parallel serving (ISSUE 14): mesh device count,
        # residual TP fraction, and the kv_pool_mesh_fallback flag —
        # gauges sampled at scrape time.
        if stats.get("sharding"):
            svc.metrics.observe_sharding(stats["sharding"])
        # Grammar-constrained decoding (ISSUE 11): forced/masked token
        # + dead-end counters — same delta-mirror pattern.
        if stats.get("grammar"):
            svc.metrics.observe_grammar(stats["grammar"])
        # Speculative decoding (ISSUE 12): drafted/accepted counters +
        # the acceptance-ratio gauge — same delta-mirror pattern.
        if stats.get("spec"):
            svc.metrics.observe_spec(stats["spec"])
        # Perf-regression sentinel (ISSUE 15): step_time_seconds
        # quantile gauges + per-rung tok/s + the breach-trip counter.
        if stats.get("steptime"):
            svc.metrics.observe_steptime(stats["steptime"])
    # Incident plane (ISSUE 15): a scrape is also a trigger-evaluation
    # round (cooldowns make redundant evaluation free), so deployments
    # with SENTINEL_EVAL_SECS=0 still capture incidents at scrape
    # cadence; captured/suppressed totals delta-mirror by trigger.
    try:
        svc.check_incidents()
    except Exception:   # pragma: no cover - defensive
        logger.exception("incident evaluation failed at scrape")
    svc.metrics.observe_incidents(svc.incidents.snapshot())
    # Weight rollout (ISSUE 13): state gauge + per-version replica
    # counts + rollbacks{cause} — the controller sits ABOVE the engine
    # seam, so it mirrors from its own health view, not stats().
    if svc.rollout is not None:
        svc.metrics.observe_rollout(svc.rollout.health())
    # Windowed throughput gauge: the batcher's own scheduler-side window
    # when it reports one (counts every finish, including streams), else
    # the service-side window fed by the response handlers.
    svc.metrics.tokens_per_sec.set(
        stats.get("tokens_per_sec_window", svc.token_rate.rate())
    )
    svc.metrics.breaker_state.set(STATE_CODES[svc.breaker.state])
    return web.Response(body=svc.metrics.render(), content_type="text/plain")


def create_app(cfg: ServiceConfig, engine: Engine,
               executor: Optional[CommandExecutor] = None,
               metrics: Optional[Metrics] = None) -> web.Application:
    """App factory (reference module init, app.py:130-138)."""
    app = web.Application(
        middlewares=[observability_middleware, overload_middleware,
                     ratelimit_middleware, auth_middleware,
                     qos_middleware]
    )
    app["service"] = Service(cfg, engine, executor=executor, metrics=metrics)

    app.router.add_post("/kubectl-command", handle_kubectl_command)
    app.router.add_post("/kubectl-command/stream", handle_kubectl_command_stream)
    app.router.add_post("/execute", handle_execute)
    app.router.add_post("/debug/profile", handle_debug_profile)
    app.router.add_post("/debug/trace", handle_debug_profile)  # pre-rename alias
    app.router.add_get("/debug/requests", handle_debug_requests)
    app.router.add_get("/debug/requests/{id}", handle_debug_request_detail)
    app.router.add_get("/debug/chunks", handle_debug_chunks)
    app.router.add_get("/debug/ledger", handle_debug_ledger)
    app.router.add_get("/debug/incidents", handle_debug_incidents)
    app.router.add_get("/debug/incidents/{id}",
                       handle_debug_incident_detail)
    app.router.add_post("/admin/rollout", handle_admin_rollout_post)
    app.router.add_get("/admin/rollout", handle_admin_rollout_get)
    app.router.add_post("/admin/rollout/abort", handle_admin_rollout_abort)
    app.router.add_get("/health", handle_health)
    app.router.add_get("/metrics", handle_metrics)
    # /openapi.json + /docs — unauthenticated like the reference's
    # FastAPI-generated docs (app.py:131); see server/openapi.py.
    from .openapi import register as register_openapi

    register_openapi(app)

    async def _start_engine(app: web.Application) -> None:
        await app["service"].engine.start()
        # Warm the /health device-count cache, but only when the engine
        # already imported jax — a fake/openai deployment must not pay a
        # multi-second jax import before the socket binds (the first
        # health probe fills the cache lazily there instead).
        import sys

        if "jax" in sys.modules:
            _device_count(app)

    async def _stop_engine(app: web.Application) -> None:
        # The DRAIN_TIMEOUT_SECS drain itself runs at signal time in
        # server/__main__.py::_serve, while the socket still answers
        # health checks (aiohttp closes the socket before cleanup hooks
        # run, so a drain here could never 503 to the LB). This hook is
        # the final teardown — idempotent after a drain, and the only
        # stop for embedded/test usages that never send a signal.
        await app["service"].engine.stop()

    async def _start_sentinel_watcher(app: web.Application) -> None:
        # Incident watcher (ISSUE 15): a background evaluation loop, so
        # triggers fire even when nothing scrapes /metrics. 0 disables
        # it (scrape/read-driven evaluation only).
        svc: Service = app["service"]
        period = svc.cfg.sentinel_eval_secs
        if period <= 0:
            return

        async def watch() -> None:
            while True:
                await asyncio.sleep(period)
                try:
                    new = svc.check_incidents()
                    await _attach_incident_profiles(app, svc, new)
                except asyncio.CancelledError:   # teardown
                    raise
                except Exception:   # pragma: no cover - defensive
                    logger.exception("sentinel watcher failed")

        app["_sentinel_task"] = asyncio.create_task(watch())

    async def _stop_sentinel_watcher(app: web.Application) -> None:
        task = app.get("_sentinel_task")
        if task is not None:
            task.cancel()
            with suppress(asyncio.CancelledError):
                await task
            app["_sentinel_task"] = None

    app.on_startup.append(_start_engine)
    app.on_startup.append(_start_sentinel_watcher)
    app.on_cleanup.append(_stop_sentinel_watcher)
    app.on_cleanup.append(_stop_engine)
    return app
