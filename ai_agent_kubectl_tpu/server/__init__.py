"""Serving front end: HTTP API + cross-cutting middleware + execution layer.

Rebuilds the reference's API/middleware/service/cache/exec layers
(SURVEY.md §1) on aiohttp, with from-scratch implementations of the
pieces the reference delegated to third-party packages:

- rate limiting  (slowapi      → ``ratelimit.SlidingWindowLimiter``)
- TTL caching    (cachetools   → ``cache.TTLCache`` with single-flight)
- env loading    (dotenv       → ``config.load_env_file``)
- metrics        (instrumentator → ``metrics`` on prometheus_client)
"""

from .app import create_app  # noqa: F401
