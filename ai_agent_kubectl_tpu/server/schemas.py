"""Request/response schemas (reference app.py:153-174), pydantic v2.

The wire contract is kept byte-compatible with the reference:
``Query{query}``, ``ExecuteRequest{execute}``, ``CommandResponse{
kubectl_command, execution_result, execution_error, from_cache, metadata}``,
``ExecutionMetadata{start_time, end_time, duration_ms, success,
error_type?, error_code?}``.

Additions (documented, additive-only): ``CommandResponse.engine_metadata``
carries engine phase timings (queue/prefill/decode, TTFT) when a local
engine served the request — the TPU-native analog of the reference's
``duration_ms`` bookkeeping (app.py:164,227).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from pydantic import BaseModel, Field


class Query(BaseModel):
    query: str = Field(..., min_length=3, description="Natural language query for kubectl.")


class ExecuteRequest(BaseModel):
    execute: str = Field(..., description="kubectl command to execute.")


class ExecutionMetadata(BaseModel):
    start_time: str
    end_time: str
    duration_ms: float
    success: bool
    error_type: Optional[str] = None
    error_code: Optional[str] = None


class EngineMetadata(BaseModel):
    """Per-request engine phase timings (TPU-native addition; SURVEY.md §5
    tracing row)."""

    queue_ms: float = 0.0
    prefill_ms: float = 0.0
    decode_ms: float = 0.0
    detok_ms: float = 0.0
    ttft_ms: float = 0.0
    prompt_tokens: int = 0
    completion_tokens: int = 0
    tokens_per_sec: float = 0.0
    prefix_cache_hit: bool = False
    engine: str = ""


class CommandResponse(BaseModel):
    kubectl_command: str
    execution_result: Optional[Dict[str, Any]] = None
    execution_error: Optional[Dict[str, Any]] = None
    from_cache: bool = False
    metadata: ExecutionMetadata
    engine_metadata: Optional[EngineMetadata] = None
    # True when the rule-based FallbackEngine served this response because
    # the real engine was failing (DEGRADED_FALLBACK + open breaker);
    # engine_metadata.engine is then "fallback-rules".
    degraded: bool = False
    # Per-phase millisecond breakdown of this request's lifecycle
    # (obs/trace.py) — the same numbers as the Server-Timing header and
    # the /debug/requests/{id} timeline, inline for clients that want
    # them without header parsing. Additive/optional: absent when no
    # trace context was active.
    timings: Optional[Dict[str, float]] = None


class HealthResponse(BaseModel):
    """Readiness-gated health (fixes the reference's static /health,
    app.py:348-354; SURVEY.md §3.3)."""

    status: str
    engine: str = ""
    engine_ready: bool = False
    model: str = ""
    devices: int = 0
    # Failure-containment state (server/breaker.py): closed | half-open |
    # open, and whether an open breaker degrades to rule-based responses
    # instead of 503s.
    breaker: str = "closed"
    degraded_fallback: bool = False
    # Inner-ring containment (engine/containment.py): when the engine
    # last reset-and-replayed its decode state (ISO 8601) and why
    # (slot_health | scheduler_error | scheduler_death). None = never.
    last_reset: Optional[str] = None
    last_reset_cause: Optional[str] = None
    # Fleet deployments (engine/fleet.py, FLEET_SIZE > 1): the rollup —
    # replica counts by state, migration/hedge/drain/eject/rejoin
    # totals — plus a ``replicas`` list with each replica's state,
    # breaker, occupancy, and last reset/cause. None = no fleet layer.
    fleet: Optional[Dict[str, Any]] = None
    # QoS ring (engine/qos.py, ISSUE 7): per-lane queue depth, the
    # active brownout level and lane shares, preemptions in the last
    # minute, and scan-time expiry/displacement totals. None = engine
    # without the QoS scheduler (fake/openai single-sequence paths).
    qos: Optional[Dict[str, Any]] = None
    # SLO burn-rate engine (obs/slo.py, ISSUE 8): multi-window (5m/1h)
    # error-budget burn for TTFT and queue wait per lane, against the
    # SLO_TTFT_MS / SLO_INTERACTIVE_MS targets. None = engine without
    # the telemetry plane.
    slo: Optional[Dict[str, Any]] = None
    # Block-paged KV pool + radix prefix sharing (ISSUE 10,
    # engine/kv_pool.py): block counts by state (free/live/cached),
    # sharing + copy-on-write totals, and the radix tree's hit/miss
    # token counters. None = dense-KV engine (KV_POOL=false, a mesh
    # with a >1 data/pipe/seq axis, or the single-sequence/fake/openai
    # paths). TP/EP meshes serve the pool (ISSUE 14).
    kv_pool: Optional[Dict[str, Any]] = None
    # Tensor-parallel serving (ISSUE 14, parallel/sharding.py): the
    # active mesh shape + device count, the residual TP fraction the
    # f≈1 policy achieves at the decode shape, whether the KV pool is
    # mesh-sharded, and the kv_pool_mesh_fallback flag (a requested
    # pool that fell back to the dense ladder must be visible). None =
    # no serving mesh.
    sharding: Optional[Dict[str, Any]] = None
    # Grammar-constrained decoding (ISSUE 11, constrain/): the active
    # profile, compiled-grammar hash + state/class counts, forced vs
    # masked token totals, and dead ends by cause. None = GRAMMAR_DECODE
    # off or an engine without the subsystem.
    grammar: Optional[Dict[str, Any]] = None
    # Speculative decoding (ISSUE 12, engine/batcher.py): draft model
    # id, k, live/degraded state, drafted/accepted totals and the
    # acceptance ratio. None = SPEC_DECODE off or an engine without the
    # subsystem.
    spec: Optional[Dict[str, Any]] = None
    # Zero-downtime weight rollout (ISSUE 13, engine/rollout.py): the
    # state machine position, target/stable checkpoint versions, the
    # canary replica + share, the per-replica version table, and
    # cumulative rollbacks by cause. None = engine without swap support
    # (the per-replica versions also appear in the fleet section).
    rollout: Optional[Dict[str, Any]] = None
    # Perf-regression sentinel (ISSUE 15, obs/steptime.py): per-(phase,
    # bucket) step-time digests (p50/p95/p99, baseline, trailing
    # tok/s), breach verdicts, and the edge-triggered trip total; the
    # fleet rollup attributes breaches to replicas. None = engine
    # without the chunked scheduler.
    steptime: Optional[Dict[str, Any]] = None
    # Incident capture (ISSUE 15, obs/incidents.py): ring occupancy,
    # captured/suppressed totals by trigger, and the newest incident id
    # (full bundles live behind token-gated /debug/incidents).
    incidents: Optional[Dict[str, Any]] = None
