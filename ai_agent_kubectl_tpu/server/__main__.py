"""Server entrypoint: ``python -m ai_agent_kubectl_tpu.server``
(reference app.py:391-400, Dockerfile:33)."""

from __future__ import annotations

import asyncio

from aiohttp import web

from ..config import ServiceConfig
from ..logging_setup import setup_logging, startup_warnings
from .app import create_app
from .factory import build_engine


def main() -> None:
    cfg = ServiceConfig.from_env()
    logger = setup_logging(cfg.log_level, cfg.log_format)
    startup_warnings(cfg)
    logger.info("Config: %s", cfg.describe())
    if cfg.distributed_init or cfg.coordinator_address:
        # Multi-host (DCN) process group — must be up before any engine
        # touches jax.devices() (SURVEY.md §5 distributed-comm row).
        from ..parallel.distributed import init_distributed

        # Explicit ranks only when multi-process is actually configured —
        # on TPU pods JAX infers both from the runtime environment.
        explicit = cfg.num_processes > 1
        init_distributed(
            cfg.coordinator_address,
            cfg.num_processes if explicit else None,
            cfg.process_id if explicit else None,
            require=cfg.distributed_init,
        )
    engine = build_engine(cfg)
    app = create_app(cfg, engine)
    logger.info("Starting server on %s:%s (engine=%s)", cfg.host, cfg.port, cfg.engine)
    asyncio.run(_serve(cfg, app, logger))


async def _serve(cfg: ServiceConfig, app: web.Application, logger) -> None:
    """Run the site with a drain-aware shutdown: on SIGTERM/SIGINT the
    listening socket STAYS OPEN while the engine stops accepting —
    /health answers 503 so load balancers drain us, and in-flight
    generations get DRAIN_TIMEOUT_SECS to finish — and only then does the
    runner tear down. (aiohttp's run_app closes the socket before any
    shutdown hook runs, so LBs would see connection-refused instead of
    the 503 drain; reference behavior is an immediate kill, app.py:392.)"""
    import signal

    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, cfg.host, cfg.port)
    await site.start()

    stop_ev = asyncio.Event()
    force_ev = asyncio.Event()
    loop = asyncio.get_running_loop()

    def _on_signal() -> None:
        # Second signal during the drain window = operator insisting:
        # skip the remaining drain and exit now (ADVICE r4).
        if stop_ev.is_set():
            force_ev.set()
        else:
            stop_ev.set()

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, _on_signal)
        except NotImplementedError:  # pragma: no cover - non-POSIX
            pass
    await stop_ev.wait()
    logger.info("Shutdown signal: draining (up to %.0fs) while still "
                "answering health checks; signal again to skip the drain",
                cfg.drain_timeout_secs)
    engine = app["service"].engine
    drain = asyncio.ensure_future(
        engine.stop(drain_secs=cfg.drain_timeout_secs))
    force = asyncio.ensure_future(force_ev.wait())
    done, _ = await asyncio.wait({drain, force},
                                 return_when=asyncio.FIRST_COMPLETED)
    if drain not in done:
        logger.warning("Second signal: aborting drain, stopping now")
        try:
            # stop(0) sets the engine's shutdown flag, which the draining
            # stop() polls — both finish promptly.
            await engine.stop(drain_secs=0.0)
        except Exception:
            logger.exception("force stop failed; awaiting original drain")
    force.cancel()
    try:
        # Always retrieve the drain task's outcome: a stop() failure must
        # surface in the logs, not as a GC-time "exception never
        # retrieved", and teardown continues to cleanup() regardless.
        await drain
    except Exception:
        logger.exception("engine drain/stop failed during shutdown")
    # on_cleanup's engine.stop() runs again inside cleanup(); it is
    # idempotent and returns immediately on an already-stopped engine.
    await runner.cleanup()


if __name__ == "__main__":
    main()
