"""Server entrypoint: ``python -m ai_agent_kubectl_tpu.server``
(reference app.py:391-400, Dockerfile:33)."""

from __future__ import annotations

from aiohttp import web

from ..config import ServiceConfig
from ..logging_setup import setup_logging, startup_warnings
from .app import create_app
from .factory import build_engine


def main() -> None:
    cfg = ServiceConfig.from_env()
    logger = setup_logging(cfg.log_level)
    startup_warnings(cfg)
    logger.info("Config: %s", cfg.describe())
    engine = build_engine(cfg)
    app = create_app(cfg, engine)
    logger.info("Starting server on %s:%s (engine=%s)", cfg.host, cfg.port, cfg.engine)
    web.run_app(app, host=cfg.host, port=cfg.port, access_log=None)


if __name__ == "__main__":
    main()
