"""Server entrypoint: ``python -m ai_agent_kubectl_tpu.server``
(reference app.py:391-400, Dockerfile:33)."""

from __future__ import annotations

from aiohttp import web

from ..config import ServiceConfig
from ..logging_setup import setup_logging, startup_warnings
from .app import create_app
from .factory import build_engine


def main() -> None:
    cfg = ServiceConfig.from_env()
    logger = setup_logging(cfg.log_level)
    startup_warnings(cfg)
    logger.info("Config: %s", cfg.describe())
    if cfg.distributed_init or cfg.coordinator_address:
        # Multi-host (DCN) process group — must be up before any engine
        # touches jax.devices() (SURVEY.md §5 distributed-comm row).
        from ..parallel.distributed import init_distributed

        # Explicit ranks only when multi-process is actually configured —
        # on TPU pods JAX infers both from the runtime environment.
        explicit = cfg.num_processes > 1
        init_distributed(
            cfg.coordinator_address,
            cfg.num_processes if explicit else None,
            cfg.process_id if explicit else None,
            require=cfg.distributed_init,
        )
    engine = build_engine(cfg)
    app = create_app(cfg, engine)
    logger.info("Starting server on %s:%s (engine=%s)", cfg.host, cfg.port, cfg.engine)
    web.run_app(app, host=cfg.host, port=cfg.port, access_log=None)


if __name__ == "__main__":
    main()
