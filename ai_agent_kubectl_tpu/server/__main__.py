"""Server entrypoint: ``python -m ai_agent_kubectl_tpu.server``
(reference app.py:391-400, Dockerfile:33)."""

from __future__ import annotations

import asyncio

from aiohttp import web

from ..config import ServiceConfig
from ..logging_setup import setup_logging, startup_warnings
from .app import create_app
from .factory import build_engine


def main() -> None:
    cfg = ServiceConfig.from_env()
    logger = setup_logging(cfg.log_level)
    startup_warnings(cfg)
    logger.info("Config: %s", cfg.describe())
    if cfg.distributed_init or cfg.coordinator_address:
        # Multi-host (DCN) process group — must be up before any engine
        # touches jax.devices() (SURVEY.md §5 distributed-comm row).
        from ..parallel.distributed import init_distributed

        # Explicit ranks only when multi-process is actually configured —
        # on TPU pods JAX infers both from the runtime environment.
        explicit = cfg.num_processes > 1
        init_distributed(
            cfg.coordinator_address,
            cfg.num_processes if explicit else None,
            cfg.process_id if explicit else None,
            require=cfg.distributed_init,
        )
    engine = build_engine(cfg)
    app = create_app(cfg, engine)
    logger.info("Starting server on %s:%s (engine=%s)", cfg.host, cfg.port, cfg.engine)
    asyncio.run(_serve(cfg, app, logger))


async def _serve(cfg: ServiceConfig, app: web.Application, logger) -> None:
    """Run the site with a drain-aware shutdown: on SIGTERM/SIGINT the
    listening socket STAYS OPEN while the engine stops accepting —
    /health answers 503 so load balancers drain us, and in-flight
    generations get DRAIN_TIMEOUT_SECS to finish — and only then does the
    runner tear down. (aiohttp's run_app closes the socket before any
    shutdown hook runs, so LBs would see connection-refused instead of
    the 503 drain; reference behavior is an immediate kill, app.py:392.)"""
    import signal

    runner = web.AppRunner(app, access_log=None)
    await runner.setup()
    site = web.TCPSite(runner, cfg.host, cfg.port)
    await site.start()

    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, stop_ev.set)
        except NotImplementedError:  # pragma: no cover - non-POSIX
            pass
    await stop_ev.wait()
    logger.info("Shutdown signal: draining (up to %.0fs) while still "
                "answering health checks", cfg.drain_timeout_secs)
    await app["service"].engine.stop(drain_secs=cfg.drain_timeout_secs)
    # on_cleanup's engine.stop() runs again inside cleanup(); it is
    # idempotent and returns immediately on an already-stopped engine.
    await runner.cleanup()


if __name__ == "__main__":
    main()
