"""TTL + LRU response cache with single-flight coalescing.

Replaces ``cachetools.TTLCache`` (reference app.py:124-125) with a
from-scratch implementation, and fixes the documented race (quirk B4,
SURVEY.md §2.3 / §5): the reference awaits the LLM between ``cache.get``
and ``cache[k] = v`` (app.py:312-322), so concurrent identical misses each
pay a full generation. ``single_flight`` coalesces them onto one in-flight
future per key.

This is the *service-layer* query→command cache. Its HBM analog — the
system-prompt prefix-KV cache — lives in ``engine/prefix_cache.py``.
"""

from __future__ import annotations

import asyncio
import time
from collections import OrderedDict
from typing import Any, Awaitable, Callable, Dict, Generic, Hashable, Optional, Tuple, TypeVar

from ..obs.trace import trace_event

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

_MISSING = object()


class TTLCache(Generic[K, V]):
    """LRU-evicting mapping whose entries expire ``ttl`` seconds after insert.

    Semantics match cachetools.TTLCache as used by the reference: per-entry
    expiry measured from insertion, LRU eviction at ``maxsize``, ``get``
    returns default on missing/expired.
    """

    def __init__(self, maxsize: int, ttl: float, timer: Callable[[], float] = time.monotonic):
        if maxsize < 1:
            raise ValueError("maxsize must be >= 1")
        if ttl <= 0:
            raise ValueError("ttl must be > 0")
        self.maxsize = maxsize
        self.ttl = ttl
        self._timer = timer
        self._data: "OrderedDict[K, Tuple[float, V]]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def _expired(self, expires_at: float) -> bool:
        return self._timer() >= expires_at

    def _purge(self) -> None:
        now = self._timer()
        dead = [k for k, (exp, _) in self._data.items() if now >= exp]
        for k in dead:
            del self._data[k]

    def get(self, key: K, default: Any = None) -> Any:
        item = self._data.get(key, _MISSING)
        if item is _MISSING:
            self.misses += 1
            return default
        expires_at, value = item
        if self._expired(expires_at):
            del self._data[key]
            self.misses += 1
            return default
        self._data.move_to_end(key)
        self.hits += 1
        return value

    def put(self, key: K, value: V) -> None:
        self._purge()
        if key in self._data:
            del self._data[key]
        elif len(self._data) >= self.maxsize:
            self._data.popitem(last=False)
            self.evictions += 1
        self._data[key] = (self._timer() + self.ttl, value)

    # dict-style sugar matching the reference's usage (app.py:312,322)
    __setitem__ = put

    def __contains__(self, key: K) -> bool:
        return self.get(key, _MISSING) is not _MISSING

    def __len__(self) -> int:
        self._purge()
        return len(self._data)

    def clear(self) -> None:
        self._data.clear()


class SingleFlight(Generic[K, V]):
    """Coalesce concurrent async computations per key.

    If a computation for ``key`` is already in flight, later callers await
    the same result instead of launching their own (fixes B4). The supplier
    runs in its *own task*, so a waiter disconnecting (handler cancellation)
    never cancels the shared computation out from under the other waiters —
    the generation completes and lands in the cache regardless. Failed
    computations are not cached; every waiter sees the same exception.
    """

    def __init__(self) -> None:
        self._inflight: Dict[K, "asyncio.Task[V]"] = {}

    @property
    def inflight_count(self) -> int:
        return len(self._inflight)

    async def do(self, key: K, supplier: Callable[[], Awaitable[V]]) -> Tuple[V, bool]:
        """Return (value, shared) — shared=True when this call piggybacked on
        another caller's in-flight computation."""
        task = self._inflight.get(key)
        shared = task is not None
        if task is None:
            task = asyncio.get_running_loop().create_task(supplier())
            self._inflight[key] = task
            task.add_done_callback(lambda t: self._inflight.pop(key, None))
            # Don't let an all-waiters-cancelled failure surface as an
            # "exception was never retrieved" warning.
            task.add_done_callback(
                lambda t: t.exception() if not t.cancelled() else None
            )
        # shield: cancelling this caller must not cancel the shared task.
        return await asyncio.shield(task), shared


class CachedSingleFlight(Generic[K, V]):
    """TTL cache + single-flight, the composed service-layer lookup path."""

    def __init__(self, maxsize: int, ttl: float, timer: Callable[[], float] = time.monotonic):
        self.cache: TTLCache[K, V] = TTLCache(maxsize, ttl, timer)
        self.flight: SingleFlight[K, V] = SingleFlight()

    async def get_or_create(
        self, key: K, supplier: Callable[[], Awaitable[V]]
    ) -> Tuple[V, bool]:
        """Return (value, from_cache). Coalesced waiters report
        from_cache=True — from the caller's perspective the value was not
        generated for them."""
        cached: Any = self.cache.get(key, _MISSING)
        if cached is not _MISSING:
            trace_event("cache: hit")
            return cached, True

        async def fill() -> V:
            value = await supplier()
            self.cache.put(key, value)
            return value

        coalesced = key in self.flight._inflight
        trace_event("cache: miss — coalescing onto the in-flight generation"
                    if coalesced else "cache: miss — starting a generation")
        value, shared = await self.flight.do(key, fill)
        return value, shared
