"""Command safety validation (reference app.py:72-88).

The contract: a generated command is acceptable only if it
1. starts with ``"kubectl "``,
2. contains none of the shell metacharacters ``; & | ` $ ( ) < >``
   (the reference checks the two-char forms ``&&``/``||``; we reject single
   ``&``/``|`` too — strictly safer, and pipes/background jobs are never
   legitimate in a single kubectl invocation),
3. parses cleanly with ``shlex.split`` (catches unclosed quotes).

Returns a reason string for observability rather than logging inside the
predicate; ``is_safe_kubectl_command`` keeps the reference's bool signature.
"""

from __future__ import annotations

import shlex
from typing import Optional

# Reference list (app.py:79) plus single & and |.
_FORBIDDEN_CHARS = (";", "&", "|", "`", "$", "(", ")", "<", ">")

#: verbs that open interactive shells or tunnels into the cluster — a
#: natural-language command service must never execute them. The
#: grammar subsystem (ai_agent_kubectl_tpu/constrain) makes them
#: UNREPRESENTABLE when GRAMMAR_DECODE is on; this check is the outer
#: defense-in-depth ring for the unconstrained path, and
#: ``constrain.assert_safety_consistent`` cross-checks at boot that no
#: grammar profile contains any of them.
BLOCKED_VERBS = frozenset((
    "attach", "cp", "debug", "edit", "exec", "port-forward", "proxy",
))


def unsafe_reason(command: str) -> Optional[str]:
    """Return None if safe, else a human-readable reason."""
    command = command.strip()
    if not command.startswith("kubectl "):
        return "command does not start with 'kubectl '"
    found = [c for c in _FORBIDDEN_CHARS if c in command]
    if found:
        return f"command contains forbidden shell metacharacters: {' '.join(found)}"
    try:
        parts = shlex.split(command)
    except ValueError as e:
        return f"command failed shell lexing: {e}"
    if not parts or parts[0] != "kubectl":
        return "command does not tokenize to a kubectl invocation"
    if len(parts) > 1 and parts[1] in BLOCKED_VERBS:
        return (f"verb {parts[1]!r} is blocked (interactive shells and "
                "tunnels are never executed by this service)")
    return None


def is_safe_kubectl_command(command: str) -> bool:
    """Bool form matching the reference's API (app.py:72)."""
    return unsafe_reason(command) is None
