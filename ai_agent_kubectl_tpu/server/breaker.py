"""Rolling-window circuit breaker for the engine path.

The reference (and the seed) let every request ride a failing engine to
its full timeout: 60 s of held connection per doomed call. The breaker
watches engine outcomes and, after ``threshold`` failures inside
``window_secs`` (watchdog trips surface as EngineUnavailable and count),
OPENS: requests stop touching the engine and either fail fast or — with
``DEGRADED_FALLBACK=true`` — route to the rule-based FallbackEngine.
After ``recovery_secs`` it goes HALF-OPEN: exactly one probe request is
let through to the real engine; success re-CLOSES the breaker, failure
re-opens it for another ``recovery_secs``.

Single-threaded by design: all transitions happen on the event loop, so
no locks. ``threshold=0`` disables the breaker entirely (it never opens).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Deque, Optional

from ..obs.trace import trace_event

CLOSED = "closed"
HALF_OPEN = "half-open"
OPEN = "open"

#: Prometheus encoding of the state (server/metrics.py breaker_state).
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    def __init__(
        self,
        threshold: int = 5,
        window_secs: float = 30.0,
        recovery_secs: float = 15.0,
        timer: Callable[[], float] = time.monotonic,
    ):
        # Follow the sibling knobs' "0 disables" convention rather than
        # crashing the server at startup on BREAKER_WINDOW_SECS=0: a
        # non-positive window means the breaker never opens.
        if window_secs <= 0:
            threshold = 0
            window_secs = 1.0
        self.threshold = threshold
        self.window_secs = window_secs
        self.recovery_secs = max(0.0, recovery_secs)
        self._timer = timer
        self._failures: Deque[float] = deque()
        self._open = False
        self._opened_at = 0.0
        self._probe_inflight = False
        self.opens = 0          # lifetime open transitions (observability)
        # Epoch fencing for long-lived engine calls: llm_timeout (60 s)
        # routinely outlives a closed→open→half-open cycle (recovery 15 s),
        # so a call admitted BEFORE the breaker opened can report its
        # outcome while a half-open probe is in flight. Outcomes carrying
        # a stale epoch are ignored — a pre-outage success must not close
        # an open breaker, and a pre-outage failure must not clobber the
        # probe slot or restart the recovery clock.
        self._epoch = 0

    # ------------------------------------------------------------- state

    @property
    def state(self) -> str:
        if not self._open:
            return CLOSED
        if self._timer() - self._opened_at >= self.recovery_secs:
            return HALF_OPEN
        return OPEN

    def begin(self) -> Optional[int]:
        """Admission check: a call token (the current epoch) when an engine
        call may proceed, None when calls are suspended. In HALF_OPEN only
        one probe is admitted at a time; everyone else keeps the
        fallback/503 path until the probe reports back. Pass the token to
        record_success/record_failure/release_probe so outcomes from
        before the last open transition are fenced off."""
        s = self.state
        if s == CLOSED:
            return self._epoch
        if s == HALF_OPEN and not self._probe_inflight:
            self._probe_inflight = True
            trace_event("breaker: half-open — this call is the probe")
            return self._epoch
        trace_event(f"breaker: {s} — engine call suspended")
        return None

    # No side-effect-free "allow()" helper on purpose: in HALF_OPEN an
    # admission check CONSUMES the single probe slot, so any caller that
    # asked without then reporting an outcome would wedge the breaker.
    # Callers must use begin() and hold the token; pure introspection is
    # the `state` property.

    # ----------------------------------------------------------- outcomes

    def _stale(self, token: Optional[int]) -> bool:
        return token is not None and token != self._epoch

    def release_probe(self, token: Optional[int] = None) -> None:
        """Return an undecided half-open probe slot: the call ended without
        an engine outcome (client cancelled mid-probe, or the submission
        was shed as overload). Without this the breaker would wedge in
        half-open forever — _probe_inflight stuck True, begin() None for
        everyone. No-op outside half-open."""
        if self._stale(token):
            return
        self._probe_inflight = False

    def record_success(self, token: Optional[int] = None) -> None:
        if self._stale(token):
            return
        if self._open:
            # Successful half-open probe: re-close with a clean slate.
            self._failures.clear()
            self._open = False
            self._probe_inflight = False
        # Closed-state successes deliberately do NOT erase the failure
        # window: under partial failure (one bad shard failing 50% of
        # calls) interleaved successes would otherwise reset the count
        # forever and the breaker would never open — it's a rolling
        # window, not a consecutive-failure counter. Old failures age out
        # via window_secs.

    def record_failure(self, token: Optional[int] = None) -> None:
        if self._stale(token):
            return
        now = self._timer()
        if self._open:
            # A failed half-open probe: restart the recovery clock and
            # fence off any other outstanding calls from this cycle.
            self._opened_at = now
            self._probe_inflight = False
            self._epoch += 1
            trace_event("breaker: half-open probe failed — re-opening")
            return
        horizon = now - self.window_secs
        while self._failures and self._failures[0] <= horizon:
            self._failures.popleft()
        self._failures.append(now)
        trace_event(f"breaker: engine failure recorded "
                    f"({len(self._failures)}/{self.threshold} in window)")
        if self.threshold > 0 and len(self._failures) >= self.threshold:
            self._open = True
            self._opened_at = now
            self._probe_inflight = False
            self._epoch += 1
            self.opens += 1
            trace_event("breaker: threshold reached — OPENING")

    # ------------------------------------------------------ observability

    @property
    def recent_failures(self) -> int:
        horizon = self._timer() - self.window_secs
        while self._failures and self._failures[0] <= horizon:
            self._failures.popleft()
        return len(self._failures)

    @property
    def state_code(self) -> int:
        return STATE_CODES[self.state]
