"""LLM output parsing + validation (reference app.py:90-104).

Replaces LangChain's ``StrOutputParser`` subclass with a plain function.
Improvements over the reference (documented quirk B5, SURVEY.md §2.3):
- strips ```` ``` ```` fences *with* language tags (``​```bash``), which the
  reference's leading/trailing-pair check missed;
- strips a leading ``$ `` shell-prompt artifact;
- collapses the output to the first non-empty line (the prompt demands a
  single-line command; chatty models sometimes append explanations).
"""

from __future__ import annotations

from .safety import unsafe_reason


class UnsafeCommandError(ValueError):
    """Raised when the model's output fails safety validation
    (maps to HTTP 422, reference app.py:192-194)."""


def _strip_fences(text: str) -> str:
    """Strip markdown code fences, including ```bash-style language tags.

    A single-line ``​```kubectl get pods```​`` must NOT treat ``kubectl`` as
    a language tag — the first-line token after the backticks is only a tag
    when dropping it still leaves a kubectl command behind.
    """
    if not text.startswith("```"):
        return text
    body = text[3:]
    if body.endswith("```"):
        body = body[:-3]
    body = body.strip()
    first_line, _, rest = body.partition("\n")
    first_line = first_line.strip()
    if rest and not first_line.lower().startswith("kubectl"):
        # Multi-line fence whose first line is a language tag ("bash").
        return rest.strip()
    return body


def parse_llm_output(text: str) -> str:
    """Extract a validated single-line kubectl command from raw model text."""
    command = _strip_fences(text.strip()).strip()
    # Drop a leading shell prompt marker if the model emitted one.
    if command.startswith("$ "):
        command = command[2:].lstrip()
    # Keep the first non-empty line only.
    for line in command.splitlines():
        line = line.strip()
        if line:
            command = line
            break
    reason = unsafe_reason(command)
    if reason is not None:
        raise UnsafeCommandError(
            f"Generated command failed safety checks ({reason}): {command}"
        )
    return command
