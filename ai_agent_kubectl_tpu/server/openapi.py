"""Machine-readable API docs: /openapi.json + a minimal /docs page.

The reference gets OpenAPI for free from FastAPI
(``FastAPI(title="Kubectl NLP Service", version="1.0.0")``,
/root/reference/app.py:131, with per-endpoint response-code catalogs at
app.py:288-297,360-367). The aiohttp rebuild generates the equivalent
document from the SAME pydantic models the handlers validate with
(server/schemas.py) plus the route/status-code table below — so client
generators and contract tests have a schema to consume (VERDICT r4
missing #1).

The document is built once at import of the app (schemas are static) and
served as a cached JSON blob.
"""

from __future__ import annotations

import json
from typing import Dict

from aiohttp import web

from .schemas import (CommandResponse, ExecuteRequest, HealthResponse,
                      Query)

_TITLE = "Kubectl NLP Service"
_VERSION = "1.0.0"          # reference parity (app.py:131)

#: error body shape every non-2xx handler returns ({"detail": ...}).
_ERROR_SCHEMA = {
    "type": "object",
    "properties": {"detail": {}},
    "required": ["detail"],
}


def _err(desc: str) -> dict:
    return {
        "description": desc,
        "content": {"application/json": {
            "schema": {"$ref": "#/components/schemas/ErrorResponse"}}},
    }


def _resp(model: str, desc: str) -> dict:
    return {
        "description": desc,
        "content": {"application/json": {
            "schema": {"$ref": f"#/components/schemas/{model}"}}},
    }


def _body(model: str) -> dict:
    return {
        "required": True,
        "content": {"application/json": {
            "schema": {"$ref": f"#/components/schemas/{model}"}}},
    }


def build_openapi() -> Dict:
    """OpenAPI 3.1 document for the service's wire contract."""
    defs: Dict[str, dict] = {}

    def schema_of(model) -> None:
        s = model.model_json_schema(
            ref_template="#/components/schemas/{model}")
        defs.update(s.pop("$defs", {}))
        defs[model.__name__] = s

    for m in (Query, ExecuteRequest, CommandResponse, HealthResponse):
        schema_of(m)
    defs["ErrorResponse"] = _ERROR_SCHEMA

    auth_err = _err("Invalid or missing X-API-Key (only when API_AUTH_KEY "
                    "is configured)")
    rate_err = _err("Rate limit exceeded (Retry-After header set)")

    paths = {
        "/kubectl-command": {"post": {
            "summary": "Translate a natural-language query into one "
                       "kubectl command",
            "description": "Generation only — execution stays on "
                           "/execute (reference quirk B1, kept "
                           "deliberately). Served from the response "
                           "cache on repeat queries (from_cache=true). "
                           "With DEGRADED_FALLBACK=true, engine failures "
                           "degrade to deterministic rule-based responses "
                           "(degraded=true, engine_metadata.engine="
                           "\"fallback-rules\") instead of 503.",
            "requestBody": _body("Query"),
            "responses": {
                "200": _resp("CommandResponse", "Generated command with "
                             "generation-phase metadata"),
                "400": _err("Invalid input query (pydantic validation), "
                            "or an invalid grammar restriction: "
                            "X-Grammar-Profile outside the known "
                            "profiles, X-Allowed-Verbs naming verbs "
                            "outside the request's clamped grammar "
                            "profile, or either header on a "
                            "GRAMMAR_DECODE=false deployment (a "
                            "restriction the engine cannot enforce is "
                            "refused, never silently dropped)"),
                "401": auth_err,
                "410": _err("Request quarantined: it repeatedly poisoned "
                            "decode steps (NaN/Inf corruption or "
                            "step-wide faults isolated to it) past "
                            "QUARANTINE_RETRY_BUDGET. Terminal — do not "
                            "retry"),
                "422": _err("Generated command failed safety validation"),
                "429": rate_err,
                "500": _err("Internal error"),
                "503": _err("Engine unavailable (degraded start, "
                            "draining, open circuit breaker) or "
                            "overloaded — overload sheds (bounded "
                            "admission queue / MAX_INFLIGHT_REQUESTS) "
                            "carry a Retry-After header priced from the "
                            "live queue drain rate"),
                "504": _err("Generation exceeded LLM_TIMEOUT"),
            },
        }},
        "/kubectl-command/stream": {"post": {
            "summary": "Stream the generated command as SSE tokens",
            "description": "TPU-native addition for the multi-turn agent "
                           "loop: text/event-stream of token events, "
                           "terminated by 'event: done' carrying the "
                           "full validated command. The SSE response "
                           "commits to HTTP 200 before generation runs, "
                           "so engine failures arrive IN-BAND as an "
                           "'event: error' frame whose data carries the "
                           "status the non-streaming endpoint would have "
                           "returned (422 unsafe / 503 unavailable / 504 "
                           "timeout) — never as an HTTP error status.",
            "requestBody": _body("Query"),
            "responses": {
                "200": {"description": "SSE stream (text/event-stream): "
                                       "token events, then 'event: done' "
                                       "— or 'event: error' with the "
                                       "failure mapped in-band. With "
                                       "DEGRADED_FALLBACK=true an engine "
                                       "failure emits 'event: degraded' "
                                       "carrying the rule-based command, "
                                       "then 'event: done'",
                        "content": {"text/event-stream": {
                            "schema": {"type": "string"}}}},
                "400": _err("Invalid input query"),
                "401": auth_err,
                "429": rate_err,
            },
        }},
        "/execute": {"post": {
            "summary": "Execute a validated kubectl command",
            "description": "Safety-validated argv execution; execution "
                           "failures are structured 200s with "
                           "execution_error set (reference quirk B2 "
                           "fixed).",
            "requestBody": _body("ExecuteRequest"),
            "responses": {
                "200": _resp("CommandResponse", "Execution result (table/"
                             "raw parsed stdout) or structured "
                             "execution_error"),
                "400": _err("Command failed safety validation"),
                "401": auth_err,
                "429": rate_err,
                "500": _err("Internal error"),
            },
        }},
        "/health": {"get": {
            "summary": "Readiness-gated health",
            "responses": {
                "200": _resp("HealthResponse", "Engine ready"),
                "503": _resp("HealthResponse", "Degraded / starting / "
                             "draining"),
            },
        }},
        "/metrics": {"get": {
            "summary": "Prometheus metrics",
            "responses": {"200": {
                "description": "Prometheus text exposition format",
                "content": {"text/plain": {"schema": {"type": "string"}}},
            }},
        }},
        "/debug/profile": {"post": {
            "summary": "Capture an on-demand jax.profiler device trace "
                       "from the live server",
            "description": "POST /debug/profile?seconds=N (clamped to "
                           "[0.1, 30]) starts a jax.profiler capture "
                           "while live traffic keeps serving and returns "
                           "the TensorBoard-loadable trace directory. "
                           "One capture at a time (409 otherwise); the "
                           "newest few captures are retained. Gated by "
                           "API-key auth AND — when DEBUG_TOKEN is set — "
                           "an X-Debug-Token header.",
            "responses": {
                "200": {"description": "Capture summary JSON "
                                       "(trace_dir, seconds)"},
                "400": _err("seconds not a number"),
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token (only when "
                            "DEBUG_TOKEN is configured)"),
                "409": _err("A capture is already in progress"),
                "500": _err("Capture failed (backend-dependent)"),
            },
        }},
        "/debug/trace": {"post": {
            "summary": "Alias of /debug/profile (pre-rename name)",
            "responses": {
                "200": {"description": "Capture summary JSON"},
                "401": auth_err,
            },
        }},
        "/debug/requests": {"get": {
            "summary": "Flight-recorder index: the last N requests' "
                       "summaries, newest first",
            "description": "Every serving-path request — including shed "
                           "503s, rate-limited 429s, degraded fallbacks "
                           "and errors — is recorded with its full span "
                           "timeline (FLIGHT_RECORDER_SIZE ring). Quote "
                           "a response's X-Request-ID at "
                           "/debug/requests/{id} for the timeline. Same "
                           "auth/token gating as /debug/profile.",
            "responses": {
                "200": {"description": "{size, recorded, requests: "
                                       "[summaries]}"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
            },
        }},
        "/debug/requests/{id}": {"get": {
            "summary": "One request's full phase-span timeline and "
                       "event log",
            "parameters": [{
                "name": "id", "in": "path", "required": True,
                "schema": {"type": "string"},
                "description": "The request's X-Request-ID",
            }],
            "responses": {
                "200": {"description": "Trace timeline: spans "
                                       "[{phase, start_ms, end_ms, "
                                       "duration_ms}], events, status, "
                                       "flags"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
                "404": _err("Request ID not (or no longer) in the ring"),
            },
        }},
        "/debug/chunks": {"get": {
            "summary": "Decode-pipeline flight record: recent chunk "
                       "dispatch/consume/prune events + live stats",
            "description": "The batch scheduler's chunk-event ring "
                           "(timestamps, KV bucket, device n_alive, "
                           "fetch latency) plus pipeline stats — pipe "
                           "depth/occupancy, device-side termination "
                           "state, wasted decode steps, chunk totals. "
                           "Same auth/token gating as /debug/profile.",
            "parameters": [{
                "name": "limit", "in": "query", "required": False,
                "schema": {"type": "integer", "default": 100},
                "description": "Newest events to return (<=0 for none)",
            }],
            "responses": {
                "200": {"description": "{events: [...], pipeline: "
                                       "{pipe_depth, pipe_inflight, "
                                       "device_active_slots, "
                                       "wasted_decode_steps, ...}}"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
            },
        }},
        "/debug/ledger": {"get": {
            "summary": "Goodput ledger: device decode steps classified "
                       "delivered vs waste, per lane and hashed tenant",
            "description": "Every device step the engine burned, "
                           "classified delivered | replayed | preempted "
                           "| hedge_loser | wasted_masked | "
                           "quarantine_burn, with per-lane goodput "
                           "percentages, the per-tenant table (keys are "
                           "sha256 hashes — tenant keys may be API "
                           "keys), and the conservation check "
                           "(delivered + all waste classes == total "
                           "accounted steps). Same auth/token gating "
                           "as /debug/profile.",
            "responses": {
                "200": {"description": "{classes, lanes, tenants, "
                                       "total_steps, goodput_pct, "
                                       "conservation: {balanced, ...}}"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
                "404": _err("Engine exposes no goodput ledger"),
            },
        }},
        "/debug/incidents": {"get": {
            "summary": "Incident ring: anomaly-triggered evidence "
                       "bundles, newest first",
            "description": "Bundles the perf-regression sentinel filed "
                           "automatically — a step-time p99 breach, an "
                           "SLO fast-burn spike, a quarantine/grammar-"
                           "dead-end spike, KV-pool exhaustion, or the "
                           "breaker opening each assemble a bounded "
                           "bundle (flight recorder, chunk rings, "
                           "ledger/SLO/pool/spec health, config "
                           "fingerprint, weights version) under a "
                           "per-trigger cooldown. Reading runs one "
                           "trigger evaluation first. Same auth/token "
                           "gating as /debug/profile.",
            "responses": {
                "200": {"description": "{ring, captured_total, "
                                       "suppressed_total, "
                                       "last_incident_id, incidents: "
                                       "[{id, trigger, at, detail}]}"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
            },
        }},
        "/debug/incidents/{id}": {"get": {
            "summary": "One incident's full evidence bundle",
            "parameters": [{
                "name": "id", "in": "path", "required": True,
                "schema": {"type": "string"},
                "description": "Incident id from the index route",
            }],
            "responses": {
                "200": {"description": "Full bundle: trigger, detail, "
                                       "flight_recorder, chunks, "
                                       "ledger, slo, qos, kv_pool, "
                                       "spec, grammar, steptime, "
                                       "config_fingerprint, "
                                       "weights_version"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
                "404": _err("Incident not (or no longer) in the ring"),
            },
        }},
        "/admin/rollout": {
            "post": {
                "summary": "Begin a zero-downtime weight rollout "
                           "(canary → gate → promote-or-rollback)",
                "description": "Drains one canary replica, swaps it to "
                               "the versioned checkpoint (content "
                               "fingerprint = version; compiled serving "
                               "programs are reused — no re-trace), "
                               "rejoins it, steers ROLLOUT_CANARY_SHARE "
                               "of fresh traffic at it for "
                               "ROLLOUT_OBSERVE_SECS, then promotes the "
                               "remaining replicas or rolls back "
                               "automatically on SLO-burn/goodput/"
                               "counter gate breach. Same auth/token "
                               "gating as /debug/profile.",
                "requestBody": {"required": True, "content": {
                    "application/json": {"schema": {
                        "type": "object",
                        "required": ["checkpoint"],
                        "properties": {"checkpoint": {
                            "type": "string",
                            "description": "Checkpoint path to roll to",
                        }},
                    }}}},
                "responses": {
                    "202": {"description": "Rollout started; body is "
                                           "the initial status"},
                    "400": _err("Missing/invalid checkpoint path"),
                    "401": auth_err,
                    "403": _err("Invalid or missing X-Debug-Token"),
                    "404": _err("Engine has no weight-rollout support"),
                    "409": _err("A rollout is already in progress / "
                                "fleet already serves that version"),
                },
            },
            "get": {
                "summary": "Rollout status: state machine, versions, "
                           "gate verdicts, timeline, rollback history",
                "responses": {
                    "200": {"description": "{state, target_version, "
                                           "stable_version, "
                                           "canary_replica, last_gate, "
                                           "events, history, ...}"},
                    "401": auth_err,
                    "403": _err("Invalid or missing X-Debug-Token"),
                    "404": _err("Engine has no weight-rollout support"),
                },
            },
        },
        "/admin/rollout/abort": {"post": {
            "summary": "Abort the in-flight rollout (automatic "
                       "rollback, cause 'aborted')",
            "responses": {
                "200": {"description": "Rollback finished; body is the "
                                       "final status"},
                "401": auth_err,
                "403": _err("Invalid or missing X-Debug-Token"),
                "404": _err("Engine has no weight-rollout support"),
                "409": _err("No rollout in progress"),
            },
        }},
    }

    return {
        "openapi": "3.1.0",
        "info": {
            "title": _TITLE,
            "version": _VERSION,
            "description": "Natural-language → kubectl translation "
                           "service backed by an in-process JAX/TPU "
                           "inference engine.",
        },
        "paths": paths,
        "components": {
            "schemas": defs,
            "securitySchemes": {
                "ApiKeyAuth": {"type": "apiKey", "in": "header",
                               "name": "X-API-Key"},
            },
        },
        "security": [{"ApiKeyAuth": []}],
    }


_DOCS_HTML = """<!DOCTYPE html>
<html>
<head><title>{title} — API docs</title>
<style>
 body {{ font-family: system-ui, sans-serif; margin: 2rem auto;
        max-width: 56rem; line-height: 1.5; color: #1a1a1a; }}
 code, pre {{ background: #f4f4f4; padding: .15em .35em;
             border-radius: 4px; }}
 pre {{ padding: 1em; overflow-x: auto; }}
 h2 {{ border-bottom: 1px solid #ddd; padding-bottom: .3em; }}
 .method {{ font-weight: 700; color: #0b5fff; }}
</style></head>
<body>
<h1>{title} <small>v{version}</small></h1>
<p>The machine-readable contract is at <a href="/openapi.json">
<code>/openapi.json</code></a> (OpenAPI 3.1) — point client generators and
contract tests there.</p>
{sections}
</body></html>"""


def _docs_page(doc: Dict) -> str:
    sections = []
    for path, methods in doc["paths"].items():
        for method, op in methods.items():
            codes = ", ".join(sorted(op.get("responses", {})))
            sections.append(
                f"<h2><span class='method'>{method.upper()}</span> "
                f"<code>{path}</code></h2>"
                f"<p>{op.get('summary', '')}</p>"
                f"<p><small>Status codes: {codes}</small></p>"
            )
    return _DOCS_HTML.format(title=doc["info"]["title"],
                             version=doc["info"]["version"],
                             sections="\n".join(sections))


def register(app: web.Application) -> None:
    doc = build_openapi()
    blob = json.dumps(doc).encode()
    page = _docs_page(doc)

    async def handle_openapi(request: web.Request) -> web.Response:
        return web.Response(body=blob, content_type="application/json")

    async def handle_docs(request: web.Request) -> web.Response:
        return web.Response(text=page, content_type="text/html")

    app.router.add_get("/openapi.json", handle_openapi)
    app.router.add_get("/docs", handle_docs)
