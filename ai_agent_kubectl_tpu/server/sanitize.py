"""Query sanitation (reference app.py:60-68)."""

from __future__ import annotations


def sanitize_query(query: str) -> str:
    """Normalize a multi-line query to a single line with collapsed whitespace.

    Same contract as the reference's ``sanitize_query`` (app.py:60-68):
    newlines/CRs/tabs become spaces, runs of whitespace collapse to one
    space, and the result is stripped.
    """
    normalized = query.replace("\n", " ").replace("\r", " ").replace("\t", " ")
    return " ".join(normalized.split()).strip()
