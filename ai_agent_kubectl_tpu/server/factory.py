"""Engine selection from config (reference chain construction,
app.py:106-122).

On construction failure the service starts degraded and serves 503s —
same behaviour as the reference's ``chain = None`` path (app.py:119-122,
quirk B7, kept deliberately: a misconfigured model should not keep
/health and /metrics down).
"""

from __future__ import annotations

import logging

from ..config import ServiceConfig
from ..engine.fake import FakeEngine
from ..engine.openai_compat import OpenAICompatEngine
from ..engine.protocol import Engine, EngineResult, EngineUnavailable

logger = logging.getLogger(__name__)


class DegradedEngine:
    """Placeholder engine when construction failed: 503 on every call."""

    name = "degraded"
    ready = False

    def __init__(self, reason: str):
        self.reason = reason

    async def start(self) -> None:
        logger.error("Engine degraded: %s", self.reason)

    async def stop(self, drain_secs: float = 0.0) -> None:
        pass

    async def generate(self, prompt, **kw) -> EngineResult:
        raise EngineUnavailable(self.reason)

    async def generate_stream(self, prompt, **kw):
        raise EngineUnavailable(self.reason)
        yield  # pragma: no cover

    def __repr__(self):  # pragma: no cover
        return f"DegradedEngine({self.reason!r})"


def _build_inner(cfg: ServiceConfig, faults=None) -> Engine:
    if cfg.engine == "fake":
        return FakeEngine()
    if cfg.engine == "openai":
        return OpenAICompatEngine(
            api_key=cfg.openai_api_key,
            model=cfg.openai_model,
            base_url=cfg.openai_base_url,
            timeout=cfg.llm_timeout,
        )
    if cfg.engine in ("jax", "jax-batched"):
        from .. import engine as _engine_pkg  # noqa: F401

        # DECODE_BATCH_SIZE > 1 (the default) serves through the
        # continuous-batching scheduler; =1 keeps the simpler
        # single-sequence engine.
        if cfg.engine == "jax-batched" or cfg.decode_batch_size > 1:
            from ..engine.batcher import BatchedJaxEngine

            return BatchedJaxEngine.from_config(cfg, faults=faults)
        from ..engine.jax_engine import JaxEngine

        return JaxEngine.from_config(cfg)
    raise ValueError(f"Unknown ENGINE: {cfg.engine!r}")


def _build_fleet(cfg: ServiceConfig, injector) -> Engine:
    """FLEET_SIZE > 1: N replicas behind the EngineFleet facade. Each
    replica gets a replica-scoped VIEW of the one shared fault injector,
    so ``r0:scheduler:die``-style drills hit exactly the replica they
    name while counters stay on one ledger."""
    from ..engine.fleet import EngineFleet

    replicas = []
    for i in range(cfg.fleet_size):
        faults = injector.for_replica(i) if injector is not None else None
        replicas.append(_build_inner(cfg, faults=faults))
    return EngineFleet(
        replicas,
        hedge_ms=cfg.fleet_hedge_ms,
        affinity=cfg.fleet_affinity,
        migration_budget=cfg.fleet_migration_budget,
        rejoin_secs=cfg.fleet_rejoin_secs,
        drain_secs=cfg.drain_timeout_secs,
        breaker_threshold=cfg.breaker_threshold,
        breaker_window_secs=cfg.breaker_window_secs,
        breaker_recovery_secs=cfg.breaker_recovery_secs,
    )


def build_engine(cfg: ServiceConfig) -> Engine:
    # Parse FAULT_POINTS OUTSIDE the degraded-start net: a typo'd drill
    # spec must refuse to boot, not degrade-start into what looks like a
    # real outage. ONE injector serves both the engine-internal points
    # (admit/chunk, threaded into the batcher) and the generate-path
    # ChaosEngine wrapper, so fired() counts and release()/clear() see
    # every point.
    from ..testing.faults import ChaosEngine, FaultInjector

    injector = FaultInjector.from_spec(cfg.fault_points)
    if injector is not None:
        # admit/chunk/decode/scheduler are only checked by the
        # continuous-batching engine; an armed point the selected engine
        # can never fire would make the drill silently inert — refuse to
        # boot instead. (FakeChunkedEngine also speaks decode/scheduler,
        # but it is a test harness, not a factory-selectable ENGINE.)
        needs_batcher = [p for p in ("admit", "chunk", "decode", "scheduler",
                                     "tenant", "draft", "swap",
                                     "checkpoint", "offload", "onload")
                         if injector.has_any(p)]
        batched = cfg.engine in ("jax", "jax-batched") and (
            cfg.engine == "jax-batched" or cfg.decode_batch_size > 1)
        if needs_batcher and not batched:
            raise ValueError(
                f"FAULT_POINTS {needs_batcher} are only wired into the "
                "continuous-batching engine (ENGINE=jax with "
                f"DECODE_BATCH_SIZE>1); inert under ENGINE={cfg.engine!r}"
            )
        # r<idx>:-scoped drills only make sense with a fleet that HAS
        # that replica — a scoped spec that can't fire is a typo, not
        # chaos (same fail-fast rule as unknown points).
        scoped = injector.scoped_replicas()
        if scoped and max(scoped) >= cfg.fleet_size:
            raise ValueError(
                f"FAULT_POINTS names replica(s) {sorted(scoped)} but "
                f"FLEET_SIZE={cfg.fleet_size}; the drill would be inert"
            )
        # Generate-path faults wrap the WHOLE service (the ChaosEngine
        # sits above the fleet facade, replica-blind), so a replica
        # scope on "generate" could never fire — refuse to boot rather
        # than run an inert drill.
        if injector.has_any("generate") and not injector.has("generate"):
            raise ValueError(
                "FAULT_POINTS: 'generate' faults cannot be replica-"
                "scoped (the generate-path wrapper sits above the "
                "fleet); drop the r<idx>: prefix"
            )
    if cfg.fleet_size > 1 and cfg.engine == "openai":
        # Fail fast outside the degraded-start net: N clients of one
        # remote endpoint is not a fleet, and silently serving one would
        # misrepresent the FLEET_SIZE the operator asked for.
        raise ValueError("FLEET_SIZE > 1 requires a local engine "
                         "(ENGINE=jax | jax-batched | fake)")
    try:
        if cfg.fleet_size > 1:
            engine = _build_fleet(cfg, injector)
        else:
            # The single engine IS replica 0: when a drill carries an
            # "r0:" scope, hand it the replica-0 view so the drill stays
            # live at FLEET_SIZE=1 (the raw injector would check it with
            # replica=None and the scoped fault would be silently
            # inert). Unscoped drills keep the raw injector — one object
            # shared with the ChaosEngine wrapper.
            faults = injector
            if injector is not None and injector.scoped_replicas():
                faults = injector.for_replica(0)
            engine = _build_inner(cfg, faults=faults)
        if injector is not None and injector.has("generate"):
            logger.warning("FAULT_POINTS active on the generate path: %s",
                           injector.describe())
            return ChaosEngine(engine, injector)
        return engine
    except Exception as e:
        logger.exception("Failed to initialize engine; starting degraded.")
        return DegradedEngine(f"engine init failed: {e}")
