"""Prometheus metrics (reference app.py:136-138 + SURVEY.md §5 additions).

The reference exposed default HTTP metrics via
prometheus-fastapi-instrumentator. Here we register the equivalent request
counters/latency histograms on ``prometheus_client`` directly, plus the
engine-side gauges the TPU build adds: tokens/sec, batch occupancy, KV-pool
usage, TTFT histogram, cache hit counters.

A dedicated ``CollectorRegistry`` per app instance keeps tests isolated
(prometheus_client's global registry rejects duplicate registration).
"""

from __future__ import annotations

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class Metrics:
    """All service + engine metrics for one app instance."""

    content_type = CONTENT_TYPE_LATEST

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        r = self.registry

        # HTTP metrics (instrumentator parity)
        self.http_requests = Counter(
            "http_requests_total",
            "Total HTTP requests",
            ["method", "handler", "status"],
            registry=r,
        )
        self.http_latency = Histogram(
            "http_request_duration_seconds",
            "HTTP request latency",
            ["method", "handler"],
            buckets=_LATENCY_BUCKETS,
            registry=r,
        )

        # Service-layer metrics
        self.cache_hits = Counter(
            "response_cache_hits_total", "Query→command cache hits", registry=r
        )
        self.cache_misses = Counter(
            "response_cache_misses_total", "Query→command cache misses", registry=r
        )
        self.rate_limited = Counter(
            "rate_limited_total", "Requests rejected by the rate limiter", registry=r
        )
        self.unsafe_commands = Counter(
            "unsafe_commands_total",
            "Commands rejected by the safety validator",
            ["source"],  # llm | user
            registry=r,
        )
        self.executions = Counter(
            "kubectl_executions_total", "kubectl subprocess runs", ["outcome"], registry=r
        )

        # Engine metrics (TPU-native additions, SURVEY.md §5)
        self.ttft = Histogram(
            "engine_ttft_seconds", "Time to first token", buckets=_TTFT_BUCKETS, registry=r
        )
        self.gen_latency = Histogram(
            "engine_generate_seconds",
            "Full generation latency",
            buckets=_LATENCY_BUCKETS,
            registry=r,
        )
        self.tokens_generated = Counter(
            "engine_tokens_generated_total", "Completion tokens produced", registry=r
        )
        self.tokens_per_sec = Gauge(
            "engine_tokens_per_sec", "Decode throughput of the last request", registry=r
        )
        self.batch_occupancy = Gauge(
            "engine_batch_occupancy", "Active slots in the decode batch", registry=r
        )
        self.queue_depth = Gauge(
            "engine_queue_depth", "Requests waiting for a decode slot", registry=r
        )
        self.kv_pool_used = Gauge(
            "engine_kv_pages_used", "KV cache pages in use", registry=r
        )
        self.kv_pool_total = Gauge(
            "engine_kv_pages_total", "KV cache pages allocated", registry=r
        )
        self.prefix_cache_hits = Counter(
            "engine_prefix_cache_hits_total", "Prefix-KV cache hits", registry=r
        )

        # Failure-containment metrics (overload shedding / breaker /
        # degraded fallback)
        self.queue_rejections = Counter(
            "queue_rejections_total",
            "Requests shed by overload protection",
            ["layer"],  # http (inflight cap) | engine (admission queue)
            registry=r,
        )
        self.breaker_state = Gauge(
            "breaker_state",
            "Circuit breaker state (0=closed, 1=half-open, 2=open)",
            registry=r,
        )
        self.degraded_responses = Counter(
            "degraded_responses_total",
            "Responses served by the rule-based fallback engine",
            registry=r,
        )

    def render(self) -> bytes:
        return generate_latest(self.registry)
