"""Prometheus metrics (reference app.py:136-138 + SURVEY.md §5 additions).

The reference exposed default HTTP metrics via
prometheus-fastapi-instrumentator. Here we register the equivalent request
counters/latency histograms on ``prometheus_client`` directly, plus the
engine-side gauges the TPU build adds: tokens/sec, batch occupancy, KV-pool
usage, TTFT histogram, cache hit counters.

A dedicated ``CollectorRegistry`` per app instance keeps tests isolated
(prometheus_client's global registry rejects duplicate registration).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# Phase spans skew small (sub-ms safety checks next to multi-second
# decodes), so the phase histogram keeps finer low-end buckets.
_PHASE_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class WindowedRate:
    """Rolling-window event rate for the throughput gauge.

    ``engine_tokens_per_sec`` used to be ``.set()`` from each finished
    request's own throughput — so it only ever showed the LAST request
    (whichever response handler wrote last under concurrent decode, i.e.
    racy and meaningless at batch>1). It is now the average completion
    rate over a trailing window: every finished generation ``add()``s its
    token count here, and the /metrics scrape reads ``rate()``. The
    alternative (dropping the gauge for ``rate(engine_tokens_generated_
    total)`` in PromQL) was rejected because bench tooling and the probe
    scripts read the gauge directly without a Prometheus server in the
    loop; the counter remains for PromQL users who want custom windows.
    """

    def __init__(self, window_secs: float = 60.0,
                 timer: Callable[[], float] = time.monotonic):
        self.window_secs = window_secs
        self._timer = timer
        self._events: deque = deque()   # (t, count)

    def add(self, count: int, now: Optional[float] = None) -> None:
        if count <= 0:
            return
        now = self._timer() if now is None else now
        self._events.append((now, count))
        self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_secs
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def rate(self, now: Optional[float] = None) -> float:
        """Events per second averaged over the trailing window. The
        denominator is the full window, not the span of observed events —
        a single burst 50 s ago reads as its amortized rate, and an idle
        window decays to 0 instead of freezing at the last burst."""
        now = self._timer() if now is None else now
        self._prune(now)
        total = sum(c for _, c in self._events)
        return total / self.window_secs if total else 0.0


class Metrics:
    """All service + engine metrics for one app instance."""

    content_type = CONTENT_TYPE_LATEST

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        r = self.registry

        # HTTP metrics (instrumentator parity)
        self.http_requests = Counter(
            "http_requests_total",
            "Total HTTP requests",
            ["method", "handler", "status"],
            registry=r,
        )
        self.http_latency = Histogram(
            "http_request_duration_seconds",
            "HTTP request latency",
            ["method", "handler"],
            buckets=_LATENCY_BUCKETS,
            registry=r,
        )

        # Service-layer metrics
        self.cache_hits = Counter(
            "response_cache_hits_total", "Query→command cache hits", registry=r
        )
        self.cache_misses = Counter(
            "response_cache_misses_total", "Query→command cache misses", registry=r
        )
        self.rate_limited = Counter(
            "rate_limited_total", "Requests rejected by the rate limiter", registry=r
        )
        self.unsafe_commands = Counter(
            "unsafe_commands_total",
            "Commands rejected by the safety validator",
            ["source"],  # llm | user
            registry=r,
        )
        self.executions = Counter(
            "kubectl_executions_total", "kubectl subprocess runs", ["outcome"], registry=r
        )

        # Engine metrics (TPU-native additions, SURVEY.md §5)
        self.ttft = Histogram(
            "engine_ttft_seconds", "Time to first token", buckets=_TTFT_BUCKETS, registry=r
        )
        self.gen_latency = Histogram(
            "engine_generate_seconds",
            "Full generation latency",
            buckets=_LATENCY_BUCKETS,
            registry=r,
        )
        self.tokens_generated = Counter(
            "engine_tokens_generated_total", "Completion tokens produced", registry=r
        )
        # Windowed, not last-request (see WindowedRate above): set at
        # scrape time from the trailing-60s completion rate.
        self.tokens_per_sec = Gauge(
            "engine_tokens_per_sec",
            "Decode throughput averaged over the trailing 60s window",
            registry=r,
        )
        self.batch_occupancy = Gauge(
            "engine_batch_occupancy", "Active slots in the decode batch", registry=r
        )
        self.queue_depth = Gauge(
            "engine_queue_depth", "Requests waiting for a decode slot", registry=r
        )
        self.kv_pool_used = Gauge(
            "engine_kv_pages_used", "KV cache pages in use", registry=r
        )
        self.kv_pool_total = Gauge(
            "engine_kv_pages_total", "KV cache pages allocated", registry=r
        )
        self.prefix_cache_hits = Counter(
            "engine_prefix_cache_hits_total", "Prefix-KV cache hits", registry=r
        )

        # Block-paged KV pool + radix prefix sharing (ISSUE 10,
        # engine/kv_pool.py + engine/radix_cache.py). ``state`` is the
        # closed free|live|cached set (live = mapped by >=1 slot,
        # cached = held only by the radix tree). The cumulative sharing
        # totals are delta-mirrored from stats()["kv_pool"] at scrape
        # time like the pipeline/containment counters.
        self.kv_pool_blocks = Gauge(
            "kv_pool_blocks",
            "KV pool blocks by state (free | live | cached)",
            ["state"],
            registry=r,
        )
        self.kv_blocks_shared = Counter(
            "kv_blocks_shared_total",
            "Shared-block mappings handed out by the radix tree "
            "(a full prefix block mapped into another slot's table)",
            registry=r,
        )
        self.kv_cow_copies = Counter(
            "kv_cow_copies_total",
            "Copy-on-write copies of partially-filled tail blocks",
            registry=r,
        )
        self.radix_hit_tokens = Counter(
            "radix_hit_tokens_total",
            "Prompt tokens whose KV was served from the radix tree "
            "(prefill skipped)",
            registry=r,
        )
        self.radix_miss_tokens = Counter(
            "radix_miss_tokens_total",
            "Prompt tokens prefilled because no cached prefix covered "
            "them",
            registry=r,
        )
        # Two-tier KV (ISSUE 20): host-RAM tier occupancy + demote/
        # onload flow. ``cause`` on the onload-fail counter is the
        # closed HostBlockStore.ONLOAD_FAIL_CAUSES set (corrupt |
        # exhausted) — cardinality bounded by construction.
        self.kv_host_blocks = Gauge(
            "kv_host_blocks",
            "Host-tier KV blocks by state (used | free)",
            ["state"],
            registry=r,
        )
        self.kv_blocks_demoted = Counter(
            "kv_blocks_demoted_total",
            "KV blocks demoted from HBM to the host-RAM tier",
            registry=r,
        )
        self.kv_blocks_onloaded = Counter(
            "kv_blocks_onloaded_total",
            "Host-tier KV blocks re-onloaded to HBM (checksum verified)",
            registry=r,
        )
        self.kv_onload_fail = Counter(
            "kv_onload_fail_total",
            "Host-tier onload failures by cause (corrupt = checksum "
            "mismatch, chain dropped + prefill fallback; exhausted = "
            "no device block free)",
            ["cause"],
            registry=r,
        )
        self.kv_host_dropped = Counter(
            "kv_host_blocks_dropped_total",
            "Host-tier blocks discarded (LRU displacement, corrupt-"
            "chain purge, or reset drain)",
            registry=r,
        )
        self._kv_pool_seen = {"shared": 0, "cow": 0, "hit": 0, "miss": 0,
                              "demoted": 0, "onloaded": 0, "dropped": 0,
                              "fail_corrupt": 0, "fail_exhausted": 0}

        # Tensor-parallel serving (ISSUE 14, parallel/sharding.py):
        # the active mesh size, the residual TP fraction the f≈1 policy
        # achieves at the decode shape (1.0 = the layout
        # tools/tp_projection.py prices), and the loud-fallback flag
        # for a KV pool forced back to the dense ladder by a
        # data/pipe/seq mesh axis. Gauges sampled at scrape time from
        # stats()["sharding"].
        self.mesh_devices = Gauge(
            "mesh_devices",
            "Devices in the active serving mesh (0 = single device)",
            registry=r,
        )
        self.sharding_residual_fraction = Gauge(
            "sharding_residual_fraction",
            "Residual TP-shardable fraction f achieved by the active "
            "sharding policy at the decode shape (1.0 = full f~1 "
            "residual-path sharding)",
            registry=r,
        )
        self.kv_pool_mesh_fallback = Gauge(
            "kv_pool_mesh_fallback",
            "1 when KV_POOL was requested but the mesh forced the "
            "dense KV ladder (data/pipe/seq axis > 1) — a silent "
            "dense fallback must be visible",
            registry=r,
        )
        # Spec decode under the mesh (ISSUE 18): whether the draft
        # world rides the mesh sharded, and whether its KV serves
        # replicated because the draft's KV heads don't divide tp (the
        # gather fallback — correct but off the shard-local fast path).
        self.spec_draft_sharded = Gauge(
            "spec_draft_sharded",
            "1 when the speculative draft model's params/KV are "
            "sharded over the serving mesh",
            registry=r,
        )
        self.spec_draft_kv_fallback = Gauge(
            "spec_draft_kv_fallback",
            "1 when the draft's KV heads do not divide the mesh's "
            "model axis and its KV cache serves replicated (gather "
            "fallback) — a silent gather must be visible",
            registry=r,
        )
        # Ragged paged attention (ISSUE 19): which regime actually
        # serves decode attention — enum-style gauge (1 on the active
        # label) so a fallback from ragged (int8 KV, non-dividing tp,
        # CPU auto-off) is a dashboard fact, not an inference.
        self.decode_attention_regime = Gauge(
            "decode_attention_regime",
            "1 for the attention regime actually serving decode "
            "(ragged = one kernel for prefill/decode/spec-verify over "
            "the block pool; paged = single-query paged kernel; "
            "gather = dense gather over pool pages; dense = per-slot "
            "dense KV ladder)",
            ["regime"],
            registry=r,
        )

        # Decode-pipeline metrics (ISSUE 4: device-side termination +
        # deep chunk pipelining). Occupancy/config are gauges sampled at
        # scrape; the waste/chunk counters are cumulative scheduler totals
        # mirrored through ``observe_pipeline`` (delta-inc so restarts of
        # the scrape path don't double-count); fetch latencies arrive as
        # drained per-chunk samples.
        self.pipe_occupancy = Gauge(
            "decode_pipe_occupancy",
            "Speculative decode chunks currently in flight",
            registry=r,
        )
        self.pipe_depth = Gauge(
            "decode_pipe_depth",
            "Configured CHUNK_PIPE_DEPTH",
            registry=r,
        )
        self.device_active_slots = Gauge(
            "decode_device_active_slots",
            "Live slots reported by the last consumed chunk's n_alive",
            registry=r,
        )
        self.wasted_decode_steps = Counter(
            "wasted_decode_steps_total",
            "Decode steps executed for already-terminated slots "
            "(~0 with DEVICE_TERMINATION=true)",
            registry=r,
        )
        self.decode_chunks = Counter(
            "decode_chunks_total",
            "Decode chunk pipeline events",
            ["event"],  # dispatch | consume | prune
            registry=r,
        )
        self.chunk_fetch = Histogram(
            "chunk_fetch_seconds",
            "Blocking device->host fetch latency per consumed chunk",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                     0.25, 0.5, 1.0, 2.5),
            registry=r,
        )
        # Last-seen cumulative totals for the delta-inc mirror.
        self._pipe_seen = {"wasted": 0, "dispatch": 0, "consume": 0,
                           "prune": 0}

        # Failure-containment metrics (overload shedding / breaker /
        # degraded fallback)
        self.queue_rejections = Counter(
            "queue_rejections_total",
            "Requests shed by overload protection",
            ["layer"],  # http (inflight cap) | engine (admission queue)
            registry=r,
        )
        self.breaker_state = Gauge(
            "breaker_state",
            "Circuit breaker state (0=closed, 1=half-open, 2=open)",
            registry=r,
        )
        self.degraded_responses = Counter(
            "degraded_responses_total",
            "Responses served by the rule-based fallback engine",
            registry=r,
        )

        # Blast-radius containment (ISSUE 5, the inner ring): engine
        # resets by cause, terminal quarantines by reason, device health
        # trips, and tokens regenerated by reset-and-replay. Cumulative
        # totals live on the engine supervisor; scrapes delta-mirror them
        # like the pipeline counters (observe_containment).
        self.engine_resets = Counter(
            "engine_resets_total",
            "Decode-state reset-and-replay cycles",
            ["cause"],  # slot_health | scheduler_error | scheduler_death
            registry=r,
        )
        self.quarantined_requests = Counter(
            "quarantined_requests_total",
            "Requests terminally quarantined by culprit isolation",
            ["reason"],  # slot_health | step_poison
            registry=r,
        )
        self.replayed_tokens = Counter(
            "replayed_tokens_total",
            "Already-generated tokens re-spliced and replayed across "
            "engine resets (innocent-victim recovery)",
            registry=r,
        )
        self.slot_health_trips = Counter(
            "slot_health_trips_total",
            "Per-slot device health-word trips (NaN/Inf logits, "
            "out-of-range token ids) caught in the decode chunk",
            registry=r,
        )
        self._containment_seen = {"resets": {}, "quarantined": {},
                                  "health_trips": 0, "replayed_tokens": 0}

        # Engine fleet (engine/fleet.py, FLEET_SIZE > 1): replica counts
        # by lifecycle state, per-replica occupancy/breaker gauges (the
        # ``replica`` label is the replica index — cardinality bounded
        # by FLEET_SIZE), and the migration/hedge/drain/eject/rejoin
        # counters, delta-mirrored from fleet.stats() like the pipeline
        # and containment totals.
        self.fleet_replicas = Gauge(
            "fleet_replicas",
            "Fleet replicas by lifecycle state",
            ["state"],  # active | draining | ejected
            registry=r,
        )
        self.fleet_replica_occupancy = Gauge(
            "fleet_replica_occupancy",
            "Active decode slots per fleet replica",
            ["replica"],
            registry=r,
        )
        self.fleet_replica_inflight = Gauge(
            "fleet_replica_inflight",
            "Fleet requests currently dispatched to each replica",
            ["replica"],
            registry=r,
        )
        self.fleet_replica_breaker = Gauge(
            "fleet_replica_breaker_state",
            "Per-replica circuit breaker (0=closed, 1=half-open, 2=open)",
            ["replica"],
            registry=r,
        )
        self.fleet_migrations = Counter(
            "fleet_migrations_total",
            "Requests migrated across replicas (crash failover + drains)",
            registry=r,
        )
        self.fleet_migrated_tokens = Counter(
            "fleet_migrated_tokens_total",
            "Generated tokens carried across replica migrations",
            registry=r,
        )
        self.fleet_hedges = Counter(
            "fleet_hedges_total",
            "Hedged re-dispatches fired past FLEET_HEDGE_MS",
            registry=r,
        )
        self.fleet_drains = Counter(
            "fleet_drains_total",
            "Voluntary replica drains started",
            registry=r,
        )
        self.fleet_ejects = Counter(
            "fleet_ejects_total",
            "Replicas ejected from rotation (evictions)",
            registry=r,
        )
        self.fleet_rejoins = Counter(
            "fleet_rejoins_total",
            "Replicas restarted and returned to rotation",
            registry=r,
        )
        self._fleet_seen = {"migrations": 0, "migrated_tokens": 0,
                            "hedges": 0, "drains": 0, "ejects": 0,
                            "rejoins": 0}

        # QoS ring (ISSUE 7, engine/qos.py): per-lane queue depth and
        # slot occupancy gauges (the ``lane`` label is the closed
        # three-lane set — cardinality bounded by construction; tenants
        # are deliberately NEVER labels), the brownout level, and the
        # preemption/expiry/displacement counters, delta-mirrored from
        # stats()["qos"] like the pipeline/containment totals.
        self.qos_queue_depth = Gauge(
            "qos_queue_depth",
            "Requests waiting for a decode slot, by priority lane",
            ["lane"],
            registry=r,
        )
        self.qos_lane_occupancy = Gauge(
            "qos_lane_occupancy",
            "Decode slots held, by priority lane",
            ["lane"],
            registry=r,
        )
        self.qos_brownout_level = Gauge(
            "qos_brownout_level",
            "AIMD brownout level (0=none, 1=background trimmed, "
            "2=batch trimmed too)",
            registry=r,
        )
        self.preemptions = Counter(
            "qos_preemptions_total",
            "Running requests preempted out of their slot for a "
            "starved higher lane (export/replay path)",
            registry=r,
        )
        self.preempted_tokens = Counter(
            "qos_preempted_tokens_total",
            "Generated tokens carried across preempt-and-replay",
            registry=r,
        )
        self.queue_expired = Counter(
            "queue_expired_total",
            "Queued requests purged at scan time because their deadline "
            "passed (they no longer occupy MAX_QUEUE_DEPTH)",
            registry=r,
        )
        self.queue_displaced = Counter(
            "queue_displaced_total",
            "Queued requests displaced from a full queue in favour of a "
            "quieter tenant's arrival (shed prefers the flooding tenant)",
            registry=r,
        )
        self._qos_seen = {"preemptions": 0, "preempted_tokens": 0,
                          "expired": 0, "displaced": 0}

        # Goodput ledger (ISSUE 8, obs/ledger.py): every device decode
        # step classified delivered | replayed | preempted | hedge_loser
        # | wasted_masked | quarantine_burn, per priority lane. Both
        # label sets are closed (three lanes, six classes) so
        # cardinality is bounded by construction; tenants are
        # deliberately NEVER labels — the per-tenant breakdown lives
        # behind /debug/ledger only. Delta-mirrored from
        # stats()["ledger"] like the pipeline/containment totals.
        self.goodput_steps = Counter(
            "goodput_steps_total",
            "Device decode steps by accounting class and lane "
            "(delivered = goodput; the rest are waste classes)",
            ["lane", "class"],
            registry=r,
        )
        self.goodput_ratio = Gauge(
            "goodput_ratio",
            "Delivered fraction of all accounted device steps, by lane",
            ["lane"],
            registry=r,
        )
        self._ledger_seen: dict = {}

        # SLO burn-rate engine (ISSUE 8, obs/slo.py): multi-window
        # error-budget burn for TTFT and queue wait per lane. ``slo``
        # and ``lane`` are closed sets; ``window`` values come from
        # SLO_WINDOWS, validated to at most obs.slo.MAX_WINDOWS at boot.
        self.slo_burn_rate = Gauge(
            "slo_burn_rate",
            "Error-budget burn rate over the window (1.0 = spending "
            "exactly at the objective's sustainable rate)",
            ["slo", "lane", "window"],
            registry=r,
        )
        self.slo_budget_remaining = Gauge(
            "slo_error_budget_remaining",
            "Unspent fraction of the window's error budget (floor 0)",
            ["slo", "lane", "window"],
            registry=r,
        )
        self.slo_breaches = Counter(
            "slo_breaches_total",
            "Latency samples that breached their SLO target",
            ["slo", "lane"],
            registry=r,
        )
        self._slo_seen: dict = {}

        # Grammar-constrained decoding (ISSUE 11, constrain/): tokens
        # delivered by forced-run fast-forward splices vs sampled under
        # the device-side mask, and FSM dead ends by cause (``cause``
        # is a closed small set: decode | admission). Delta-mirrored
        # from stats()["grammar"] like the pipeline totals.
        self.grammar_forced_tokens = Counter(
            "grammar_forced_tokens_total",
            "Tokens delivered by forced-run fast-forward splices "
            "(single-successor FSM chains written as one suffix "
            "prefill instead of decoded token-by-token)",
            registry=r,
        )
        self.grammar_masked_steps = Counter(
            "grammar_masked_steps_total",
            "Decode steps sampled under the grammar's device-side "
            "logit mask",
            registry=r,
        )
        self.grammar_dead_ends = Counter(
            "grammar_dead_end_total",
            "Slots frozen in a grammar dead end (no legal token from "
            "the current FSM state)",
            ["cause"],
            registry=r,
        )
        self._grammar_seen = {"forced": 0, "masked": 0, "dead": {}}

        # Speculative decoding (ISSUE 12, engine/batcher.py): draft
        # proposals vs verifier acceptances, and the derived acceptance
        # ratio — the first-class signal of whether the 2B is actually
        # buying the 7B extra tokens per weight read. Delta-mirrored
        # from stats()["spec"] like the grammar totals; the ratio is a
        # gauge set from the cumulative counters at scrape time.
        self.spec_drafted_tokens = Counter(
            "spec_drafted_tokens_total",
            "Draft-model token proposals submitted to the verifier",
            registry=r,
        )
        self.spec_accepted_tokens = Counter(
            "spec_accepted_tokens_total",
            "Draft proposals the target model's verify step accepted "
            "(each one is a transcript token that cost no extra "
            "target forward)",
            registry=r,
        )
        self.spec_acceptance_ratio = Gauge(
            "spec_acceptance_ratio",
            "Cumulative accepted/drafted ratio of speculative decoding",
            registry=r,
        )
        self._spec_seen = {"drafted": 0, "accepted": 0}

        # Zero-downtime weight rollout (ISSUE 13, engine/rollout.py):
        # the state machine's current state (encoded by index into the
        # closed ROLLOUT_STATES set), replicas by serving weights
        # version (cardinality bounded per scrape — stale version
        # labels are zeroed, and at most FLEET_SIZE+1 versions can be
        # live at once), and automatic rollbacks by cause (closed
        # ROLLBACK_CAUSES set), delta-mirrored from the controller's
        # cumulative totals like every other subsystem.
        self.rollout_state = Gauge(
            "rollout_state",
            "Weight-rollout state machine position (0=idle, 1=draining, "
            "2=swapping, 3=warming, 4=observing, 5=promoting, "
            "6=rolling_back, 7=rolled_back, 8=complete, 9=failed)",
            registry=r,
        )
        self.rollout_replicas = Gauge(
            "rollout_replicas",
            "Fleet replicas by the checkpoint version they serve",
            ["version"],
            registry=r,
        )
        self.rollout_rollbacks = Counter(
            "rollout_rollbacks_total",
            "Automatic weight-rollout rollbacks",
            ["cause"],
            registry=r,
        )
        self._rollout_seen: dict = {}
        self._rollout_versions_seen: set = set()

        # Perf-regression sentinel (ISSUE 15, obs/steptime.py): per-
        # (phase, bucket) step-time quantiles set at scrape time from
        # the engine's bounded digests. ``phase`` is the closed
        # obs.STEP_PHASES set; ``bucket`` values come from the engine's
        # KV/prefill bucket ladders — cardinality bounded by config,
        # like the SLO windows. The breach-trip counter delta-mirrors
        # the sentinel's edge-triggered total.
        self.step_time = Gauge(
            "step_time_seconds",
            "Per-chunk device step time quantiles by phase and bucket "
            "(p50 | p95 | p99 over the sentinel's trailing window)",
            ["phase", "bucket", "quantile"],
            registry=r,
        )
        self.step_tokens_per_sec = Gauge(
            "step_tokens_per_sec",
            "Trailing tokens/sec produced at this (phase, bucket) rung",
            ["phase", "bucket"],
            registry=r,
        )
        self.steptime_trips = Counter(
            "steptime_breach_trips_total",
            "Step-time sentinel breach transitions (p99 crossed the "
            "baseline envelope; edge-triggered, not per scrape)",
            registry=r,
        )
        self._steptime_seen = 0

        # Incident capture (ISSUE 15, obs/incidents.py): bundles
        # captured vs suppressed-by-cooldown, by trigger (the closed
        # obs.incidents.TRIGGERS set).
        self.incidents_captured = Counter(
            "incidents_captured_total",
            "Incident bundles assembled into the /debug/incidents ring",
            ["trigger"],
            registry=r,
        )
        self.incidents_suppressed = Counter(
            "incidents_suppressed_total",
            "Trigger firings swallowed by the per-trigger cooldown "
            "(counted, never captured — bounds capture overhead)",
            ["trigger"],
            registry=r,
        )
        self._incidents_seen = {"captured": {}, "suppressed": {}}

        # Request-lifecycle phase attribution (obs/trace.py): where a
        # request's wall time went. The ``phase`` label is drawn from the
        # fixed obs.PHASES allowlist — cardinality is bounded by
        # construction, a span with any other name is never observed here.
        self.request_phase = Histogram(
            "request_phase_seconds",
            "Per-request time spent in each lifecycle phase",
            ["phase"],
            buckets=_PHASE_BUCKETS,
            registry=r,
        )

    def observe_pipeline(self, stats: dict) -> None:
        """Mirror the batcher's decode-pipeline stats into Prometheus at
        scrape time: gauges set directly, cumulative scheduler totals
        turned into counter increments (the engine owns the running
        total; a scrape only publishes the delta since the last one), and
        drained chunk-fetch samples observed into the histogram."""
        self.pipe_occupancy.set(stats.get("pipe_inflight", 0))
        self.pipe_depth.set(stats.get("pipe_depth", 0))
        self.device_active_slots.set(stats.get("device_active_slots", 0))
        wasted = stats.get("wasted_decode_steps", 0)
        if wasted > self._pipe_seen["wasted"]:
            self.wasted_decode_steps.inc(wasted - self._pipe_seen["wasted"])
            self._pipe_seen["wasted"] = wasted
        for event, key in (("dispatch", "chunks_dispatched"),
                           ("consume", "chunks_consumed"),
                           ("prune", "chunks_pruned")):
            total = stats.get(key, 0)
            if total > self._pipe_seen[event]:
                self.decode_chunks.labels(event=event).inc(
                    total - self._pipe_seen[event])
                self._pipe_seen[event] = total
        for s in stats.get("chunk_fetch_secs", ()):
            self.chunk_fetch.observe(s)

    def observe_kv_pool(self, pool: dict) -> None:
        """Mirror the engine's KV-pool stats (stats()["kv_pool"]) into
        Prometheus at scrape time — block-state gauges set directly,
        cumulative sharing/COW/radix totals delta-inc'd like the
        pipeline/containment mirrors."""
        for state in ("free", "live", "cached"):
            self.kv_pool_blocks.labels(state=state).set(pool.get(state, 0))
        # ISSUE 19: single-chip engines surface the attention regime on
        # the pool body (sharding_health is None without a mesh) — the
        # mesh path sets the same gauge from observe_sharding.
        self._set_attention_regime(pool.get("attention_regime"))
        seen = self._kv_pool_seen
        radix = pool.get("radix") or {}
        for key, counter, total in (
                ("shared", self.kv_blocks_shared,
                 pool.get("shared_mapped_total", 0)),
                ("cow", self.kv_cow_copies,
                 pool.get("cow_copies_total", 0)),
                ("hit", self.radix_hit_tokens, radix.get("hit_tokens", 0)),
                ("miss", self.radix_miss_tokens,
                 radix.get("miss_tokens", 0))):
            if total > seen[key]:
                counter.inc(total - seen[key])
                seen[key] = total
        # Two-tier host tier (ISSUE 20): absent when HOST_KV_BLOCKS=0 —
        # the gauges/counters simply never move.
        host = pool.get("host_tier")
        if host:
            self.kv_host_blocks.labels(state="used").set(
                host.get("used", 0))
            self.kv_host_blocks.labels(state="free").set(
                host.get("free", 0))
            fails = host.get("onload_fail_total") or {}
            for key, counter, total in (
                    ("demoted", self.kv_blocks_demoted,
                     host.get("demoted_total", 0)),
                    ("onloaded", self.kv_blocks_onloaded,
                     host.get("onloaded_total", 0)),
                    ("dropped", self.kv_host_dropped,
                     host.get("dropped_total", 0)),
                    ("fail_corrupt",
                     self.kv_onload_fail.labels(cause="corrupt"),
                     fails.get("corrupt", 0)),
                    ("fail_exhausted",
                     self.kv_onload_fail.labels(cause="exhausted"),
                     fails.get("exhausted", 0))):
                if total > seen[key]:
                    counter.inc(total - seen[key])
                    seen[key] = total

    def observe_sharding(self, sharding: dict) -> None:
        """Mirror the engine's sharding view (stats()["sharding"],
        ISSUE 14) into Prometheus at scrape time — plain gauges (all
        three are config-derived states, not cumulative totals)."""
        self.mesh_devices.set(sharding.get("devices", 0))
        self.sharding_residual_fraction.set(
            sharding.get("residual_tp_fraction", 0.0))
        self.kv_pool_mesh_fallback.set(
            1 if sharding.get("kv_pool_mesh_fallback") else 0)
        self.spec_draft_sharded.set(
            1 if sharding.get("draft_sharded") else 0)
        self.spec_draft_kv_fallback.set(
            1 if sharding.get("draft_kv_fallback") else 0)
        self._set_attention_regime(sharding.get("attention_regime"))

    def _set_attention_regime(self, active) -> None:
        if not active:
            return
        for regime in ("ragged", "paged", "gather", "dense"):
            self.decode_attention_regime.labels(regime=regime).set(
                1 if regime == active else 0)

    def observe_containment(self, stats: dict) -> None:
        """Delta-mirror the engine supervisor's containment totals
        (stats()["containment"]) into the labelled Prometheus counters —
        same scrape-time pattern as ``observe_pipeline``."""
        c = stats.get("containment")
        if not c:
            return
        seen = self._containment_seen
        for cause, total in c.get("resets", {}).items():
            prev = seen["resets"].get(cause, 0)
            if total > prev:
                self.engine_resets.labels(cause=cause).inc(total - prev)
                seen["resets"][cause] = total
        for reason, total in c.get("quarantined", {}).items():
            prev = seen["quarantined"].get(reason, 0)
            if total > prev:
                self.quarantined_requests.labels(reason=reason).inc(
                    total - prev)
                seen["quarantined"][reason] = total
        for key, counter in (("health_trips", self.slot_health_trips),
                             ("replayed_tokens", self.replayed_tokens)):
            total = c.get(key, 0)
            if total > seen[key]:
                counter.inc(total - seen[key])
                seen[key] = total

    #: breaker-state encoding for the per-replica gauge (kept inline —
    #: importing server.breaker here would be a layering inversion).
    _BREAKER_CODES = {"closed": 0, "half-open": 1, "open": 2}

    def observe_fleet(self, fleet: dict) -> None:
        """Mirror the fleet rollup (stats()["fleet"]) into Prometheus at
        scrape time — gauges set directly, cumulative fleet counters
        delta-inc'd like the pipeline/containment totals."""
        for state in ("active", "draining", "ejected"):
            self.fleet_replicas.labels(state=state).set(
                fleet.get(state, 0))
        for rep in fleet.get("replicas", ()):
            label = str(rep.get("replica", "?"))
            self.fleet_replica_occupancy.labels(replica=label).set(
                rep.get("occupancy", 0))
            self.fleet_replica_inflight.labels(replica=label).set(
                rep.get("inflight", 0))
            self.fleet_replica_breaker.labels(replica=label).set(
                self._BREAKER_CODES.get(rep.get("breaker"), 0))
        seen = self._fleet_seen
        for key, counter in (("migrations", self.fleet_migrations),
                             ("migrated_tokens", self.fleet_migrated_tokens),
                             ("hedges", self.fleet_hedges),
                             ("drains", self.fleet_drains),
                             ("ejects", self.fleet_ejects),
                             ("rejoins", self.fleet_rejoins)):
            total = fleet.get(key, 0)
            if total > seen[key]:
                counter.inc(total - seen[key])
                seen[key] = total

    def observe_qos(self, qos: dict) -> None:
        """Mirror the engine's QoS stats (stats()["qos"]) into
        Prometheus at scrape time — gauges set directly, cumulative
        totals delta-inc'd like the pipeline/containment mirrors."""
        for lane, n in (qos.get("lane_depth") or {}).items():
            self.qos_queue_depth.labels(lane=lane).set(n)
        for lane, n in (qos.get("lane_occupancy") or {}).items():
            self.qos_lane_occupancy.labels(lane=lane).set(n)
        self.qos_brownout_level.set(qos.get("brownout_level", 0))
        seen = self._qos_seen
        for key, counter in (("preemptions", self.preemptions),
                             ("preempted_tokens", self.preempted_tokens),
                             ("expired", self.queue_expired),
                             ("displaced", self.queue_displaced)):
            total = qos.get(key, 0)
            if total > seen[key]:
                counter.inc(total - seen[key])
                seen[key] = total

    def observe_ledger(self, ledger: dict) -> None:
        """Mirror the goodput ledger's lane table (stats()["ledger"])
        into Prometheus at scrape time — per-(lane, class) cumulative
        totals delta-inc'd, the per-lane goodput ratio set directly."""
        from ..obs.ledger import LEDGER_CLASSES

        for lane, row in (ledger.get("lanes") or {}).items():
            seen = self._ledger_seen.setdefault(lane, {})
            for cls in LEDGER_CLASSES:
                total = row.get(cls, 0)
                prev = seen.get(cls, 0)
                if total > prev:
                    # positional labels: "class" is a Python keyword.
                    self.goodput_steps.labels(lane, cls).inc(total - prev)
                    seen[cls] = total
            lane_total = row.get("total", 0)
            if lane_total:
                self.goodput_ratio.labels(lane=lane).set(
                    row.get("delivered", 0) / lane_total)

    def observe_grammar(self, grammar: dict) -> None:
        """Delta-mirror the engine's grammar totals
        (stats()["grammar"]) into Prometheus at scrape time — same
        pattern as the pipeline/containment mirrors."""
        seen = self._grammar_seen
        for key, counter, total in (
                ("forced", self.grammar_forced_tokens,
                 grammar.get("forced_tokens_total", 0)),
                ("masked", self.grammar_masked_steps,
                 grammar.get("masked_steps_total", 0))):
            if total > seen[key]:
                counter.inc(total - seen[key])
                seen[key] = total
        for cause, total in (grammar.get("dead_ends_total") or {}).items():
            prev = seen["dead"].get(cause, 0)
            if total > prev:
                self.grammar_dead_ends.labels(cause=cause).inc(
                    total - prev)
                seen["dead"][cause] = total

    def observe_spec(self, spec: dict) -> None:
        """Delta-mirror the engine's speculative-decode totals
        (stats()["spec"]) into Prometheus at scrape time — counters
        delta-inc'd like the grammar mirror, the acceptance ratio set
        as a gauge from the cumulative totals."""
        seen = self._spec_seen
        for key, counter, total in (
                ("drafted", self.spec_drafted_tokens,
                 spec.get("drafted_tokens_total", 0)),
                ("accepted", self.spec_accepted_tokens,
                 spec.get("accepted_tokens_total", 0))):
            if total > seen[key]:
                counter.inc(total - seen[key])
                seen[key] = total
        drafted = spec.get("drafted_tokens_total", 0)
        if drafted:
            self.spec_acceptance_ratio.set(
                spec.get("accepted_tokens_total", 0) / drafted)

    def observe_rollout(self, rollout: dict) -> None:
        """Mirror the rollout controller's health view into Prometheus
        at scrape time — state gauge set by index, per-version replica
        counts set (stale version labels zeroed so a completed rollout
        doesn't leave the old version reading 1 forever), rollback
        causes delta-inc'd."""
        from ..engine.rollout import ROLLOUT_STATES

        try:
            code = ROLLOUT_STATES.index(rollout.get("state", "idle"))
        except ValueError:   # pragma: no cover - future state
            code = 0
        self.rollout_state.set(code)
        versions: dict = {}
        for v in (rollout.get("replica_versions") or {}).values():
            if v:
                versions[v] = versions.get(v, 0) + 1
        for v, n in versions.items():
            self.rollout_replicas.labels(version=v).set(n)
            self._rollout_versions_seen.add(v)
        for v in self._rollout_versions_seen - set(versions):
            self.rollout_replicas.labels(version=v).set(0)
        for cause, total in (rollout.get("rollbacks_total")
                             or {}).items():
            prev = self._rollout_seen.get(cause, 0)
            if total > prev:
                self.rollout_rollbacks.labels(cause=cause).inc(
                    total - prev)
                self._rollout_seen[cause] = total

    def observe_steptime(self, st: dict) -> None:
        """Mirror the step-time sentinel snapshot (stats()["steptime"])
        into Prometheus at scrape time — quantile/rate gauges set
        directly, the edge-triggered trip total delta-inc'd."""
        for d in (st.get("digests") or {}).values():
            phase = str(d.get("phase", "?"))
            bucket = str(d.get("bucket", "?"))
            for q, key in (("p50", "p50_ms"), ("p95", "p95_ms"),
                           ("p99", "p99_ms")):
                self.step_time.labels(
                    phase=phase, bucket=bucket, quantile=q).set(
                    float(d.get(key, 0.0)) / 1000.0)
            self.step_tokens_per_sec.labels(
                phase=phase, bucket=bucket).set(d.get("tok_s", 0.0))
        total = int(st.get("trips_total", 0))
        if total > self._steptime_seen:
            self.steptime_trips.inc(total - self._steptime_seen)
            self._steptime_seen = total

    def observe_incidents(self, snap: dict) -> None:
        """Delta-mirror the incident manager's captured/suppressed
        totals (by trigger) into Prometheus at scrape time."""
        seen = self._incidents_seen
        for key, counter in (("captured", self.incidents_captured),
                             ("suppressed", self.incidents_suppressed)):
            for trigger, total in (snap.get(f"{key}_total")
                                   or {}).items():
                prev = seen[key].get(trigger, 0)
                if total > prev:
                    counter.labels(trigger=trigger).inc(total - prev)
                    seen[key][trigger] = total

    def observe_slo(self, slo: dict) -> None:
        """Mirror the SLO burn snapshot (stats()["slo"]) into
        Prometheus: per-window burn/budget gauges set directly,
        cumulative breach counts delta-inc'd."""
        for name, body in (slo.get("slos") or {}).items():
            for lane, row in (body.get("lanes") or {}).items():
                for window, win in (row.get("windows") or {}).items():
                    self.slo_burn_rate.labels(
                        slo=name, lane=lane, window=window).set(
                        win.get("burn_rate", 0.0))
                    self.slo_budget_remaining.labels(
                        slo=name, lane=lane, window=window).set(
                        win.get("budget_remaining", 1.0))
                total = row.get("breaches_total", 0)
                prev = self._slo_seen.get((name, lane), 0)
                if total > prev:
                    self.slo_breaches.labels(slo=name, lane=lane).inc(
                        total - prev)
                    self._slo_seen[(name, lane)] = total

    def render(self) -> bytes:
        return generate_latest(self.registry)
