"""Prometheus metrics (reference app.py:136-138 + SURVEY.md §5 additions).

The reference exposed default HTTP metrics via
prometheus-fastapi-instrumentator. Here we register the equivalent request
counters/latency histograms on ``prometheus_client`` directly, plus the
engine-side gauges the TPU build adds: tokens/sec, batch occupancy, KV-pool
usage, TTFT histogram, cache hit counters.

A dedicated ``CollectorRegistry`` per app instance keeps tests isolated
(prometheus_client's global registry rejects duplicate registration).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Callable, Optional

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)
from prometheus_client.exposition import CONTENT_TYPE_LATEST

_TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.15, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)
_LATENCY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)
# Phase spans skew small (sub-ms safety checks next to multi-second
# decodes), so the phase histogram keeps finer low-end buckets.
_PHASE_BUCKETS = (0.0005, 0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                  1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


class WindowedRate:
    """Rolling-window event rate for the throughput gauge.

    ``engine_tokens_per_sec`` used to be ``.set()`` from each finished
    request's own throughput — so it only ever showed the LAST request
    (whichever response handler wrote last under concurrent decode, i.e.
    racy and meaningless at batch>1). It is now the average completion
    rate over a trailing window: every finished generation ``add()``s its
    token count here, and the /metrics scrape reads ``rate()``. The
    alternative (dropping the gauge for ``rate(engine_tokens_generated_
    total)`` in PromQL) was rejected because bench tooling and the probe
    scripts read the gauge directly without a Prometheus server in the
    loop; the counter remains for PromQL users who want custom windows.
    """

    def __init__(self, window_secs: float = 60.0,
                 timer: Callable[[], float] = time.monotonic):
        self.window_secs = window_secs
        self._timer = timer
        self._events: deque = deque()   # (t, count)

    def add(self, count: int, now: Optional[float] = None) -> None:
        if count <= 0:
            return
        now = self._timer() if now is None else now
        self._events.append((now, count))
        self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.window_secs
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    def rate(self, now: Optional[float] = None) -> float:
        """Events per second averaged over the trailing window. The
        denominator is the full window, not the span of observed events —
        a single burst 50 s ago reads as its amortized rate, and an idle
        window decays to 0 instead of freezing at the last burst."""
        now = self._timer() if now is None else now
        self._prune(now)
        total = sum(c for _, c in self._events)
        return total / self.window_secs if total else 0.0


class Metrics:
    """All service + engine metrics for one app instance."""

    content_type = CONTENT_TYPE_LATEST

    def __init__(self) -> None:
        self.registry = CollectorRegistry()
        r = self.registry

        # HTTP metrics (instrumentator parity)
        self.http_requests = Counter(
            "http_requests_total",
            "Total HTTP requests",
            ["method", "handler", "status"],
            registry=r,
        )
        self.http_latency = Histogram(
            "http_request_duration_seconds",
            "HTTP request latency",
            ["method", "handler"],
            buckets=_LATENCY_BUCKETS,
            registry=r,
        )

        # Service-layer metrics
        self.cache_hits = Counter(
            "response_cache_hits_total", "Query→command cache hits", registry=r
        )
        self.cache_misses = Counter(
            "response_cache_misses_total", "Query→command cache misses", registry=r
        )
        self.rate_limited = Counter(
            "rate_limited_total", "Requests rejected by the rate limiter", registry=r
        )
        self.unsafe_commands = Counter(
            "unsafe_commands_total",
            "Commands rejected by the safety validator",
            ["source"],  # llm | user
            registry=r,
        )
        self.executions = Counter(
            "kubectl_executions_total", "kubectl subprocess runs", ["outcome"], registry=r
        )

        # Engine metrics (TPU-native additions, SURVEY.md §5)
        self.ttft = Histogram(
            "engine_ttft_seconds", "Time to first token", buckets=_TTFT_BUCKETS, registry=r
        )
        self.gen_latency = Histogram(
            "engine_generate_seconds",
            "Full generation latency",
            buckets=_LATENCY_BUCKETS,
            registry=r,
        )
        self.tokens_generated = Counter(
            "engine_tokens_generated_total", "Completion tokens produced", registry=r
        )
        # Windowed, not last-request (see WindowedRate above): set at
        # scrape time from the trailing-60s completion rate.
        self.tokens_per_sec = Gauge(
            "engine_tokens_per_sec",
            "Decode throughput averaged over the trailing 60s window",
            registry=r,
        )
        self.batch_occupancy = Gauge(
            "engine_batch_occupancy", "Active slots in the decode batch", registry=r
        )
        self.queue_depth = Gauge(
            "engine_queue_depth", "Requests waiting for a decode slot", registry=r
        )
        self.kv_pool_used = Gauge(
            "engine_kv_pages_used", "KV cache pages in use", registry=r
        )
        self.kv_pool_total = Gauge(
            "engine_kv_pages_total", "KV cache pages allocated", registry=r
        )
        self.prefix_cache_hits = Counter(
            "engine_prefix_cache_hits_total", "Prefix-KV cache hits", registry=r
        )

        # Failure-containment metrics (overload shedding / breaker /
        # degraded fallback)
        self.queue_rejections = Counter(
            "queue_rejections_total",
            "Requests shed by overload protection",
            ["layer"],  # http (inflight cap) | engine (admission queue)
            registry=r,
        )
        self.breaker_state = Gauge(
            "breaker_state",
            "Circuit breaker state (0=closed, 1=half-open, 2=open)",
            registry=r,
        )
        self.degraded_responses = Counter(
            "degraded_responses_total",
            "Responses served by the rule-based fallback engine",
            registry=r,
        )

        # Request-lifecycle phase attribution (obs/trace.py): where a
        # request's wall time went. The ``phase`` label is drawn from the
        # fixed obs.PHASES allowlist — cardinality is bounded by
        # construction, a span with any other name is never observed here.
        self.request_phase = Histogram(
            "request_phase_seconds",
            "Per-request time spent in each lifecycle phase",
            ["phase"],
            buckets=_PHASE_BUCKETS,
            registry=r,
        )

    def render(self) -> bytes:
        return generate_latest(self.registry)
