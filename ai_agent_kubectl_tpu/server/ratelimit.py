"""Per-client sliding-window rate limiting.

Replaces slowapi's ``Limiter`` (reference app.py:127-134, 298, 368) with a
from-scratch sliding-window counter keyed by remote address. The reference
applied the same limit twice (middleware default + per-route decorator,
quirk B3); here one enforcement point covers the rate-limited routes.

429 responses carry ``Retry-After`` and the conventional
``X-RateLimit-{Limit,Remaining,Reset}`` headers.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable, Deque, Dict, Optional, Tuple


def ceil_seconds(seconds: float) -> int:
    """Whole-second ceiling for Retry-After-style header values (shared by
    the rate limiter and the overload-shed responses in server/app.py)."""
    return math.ceil(seconds) if seconds > 0 else 0


def client_key(remote: Optional[str], forwarded_for: Optional[str],
               trust_proxy: bool) -> str:
    """Rate-limit bucket key for one request.

    Behind a fronting router tier (the fleet deployment shape) every
    request arrives from ONE upstream peer IP — keying on it would give
    the whole user base a single shared quota. With ``trust_proxy``
    (TRUST_PROXY / TRUST_PROXY_HEADERS) the leftmost ``X-Forwarded-For``
    hop — the untrusted client as the first proxy saw it — keys the
    bucket instead. Without it the raw peer IP stays authoritative: a
    direct client could otherwise mint a fresh quota per request by
    forging the header."""
    if trust_proxy and forwarded_for:
        hops = [h.strip() for h in forwarded_for.split(",") if h.strip()]
        if hops:
            return hops[0]
    return remote or "unknown"


class SlidingWindowLimiter:
    """Classic sliding-window-log limiter: at most ``count`` events per
    ``window`` seconds per key. Exact (no bucketing artifacts), O(count)
    memory per active key, with idle-key garbage collection."""

    def __init__(
        self,
        count: int,
        window: float,
        timer: Callable[[], float] = time.monotonic,
        gc_interval: float = 60.0,
    ):
        if count < 1:
            raise ValueError("count must be >= 1")
        self.count = count
        self.window = window
        self._timer = timer
        self._events: Dict[str, Deque[float]] = {}
        self._gc_interval = gc_interval
        self._last_gc = timer()

    def _gc(self, now: float) -> None:
        if now - self._last_gc < self._gc_interval:
            return
        self._last_gc = now
        horizon = now - self.window
        dead = [k for k, dq in self._events.items() if not dq or dq[-1] <= horizon]
        for k in dead:
            del self._events[k]

    def check(self, key: str) -> Tuple[bool, int, float]:
        """Record an attempt for ``key``.

        Returns (allowed, remaining, retry_after_seconds). Only allowed
        events consume quota.
        """
        now = self._timer()
        self._gc(now)
        dq = self._events.get(key)
        if dq is None:
            dq = self._events[key] = deque()
        horizon = now - self.window
        while dq and dq[0] <= horizon:
            dq.popleft()
        if len(dq) >= self.count:
            retry_after = dq[0] + self.window - now
            return False, 0, max(retry_after, 0.0)
        dq.append(now)
        return True, self.count - len(dq), 0.0

    def headers(self, remaining: int, retry_after: float) -> Dict[str, str]:
        # X-RateLimit-Reset is delta-seconds until quota frees. The old
        # value was int(monotonic + retry_after) — a process-relative
        # timestamp no client could interpret.
        h = {
            "X-RateLimit-Limit": str(self.count),
            "X-RateLimit-Remaining": str(max(remaining, 0)),
            "X-RateLimit-Reset": str(ceil_seconds(retry_after)),
        }
        if retry_after > 0:
            h["Retry-After"] = str(max(1, ceil_seconds(retry_after)))
        return h
