"""Engine fleet: replicated engines behind one ``Engine``-protocol facade.

ROADMAP item 5's architecture step from "one engine" to "a fleet": the
``EngineFleet`` runs N engine replicas (real ``BatchedJaxEngine``s in
production, ``FakeChunkedEngine``s in tests — anything speaking the
Engine protocol works, with degraded capabilities) behind a front router
and escalates PR 5's containment machinery from slot level to replica
level. Four mechanisms:

1. **Health-aware routing** — every dispatch picks a replica by live
   signals only: replica state (active / draining / ejected), engine
   readiness, the per-replica circuit breaker, and in-flight occupancy
   (least-loaded wins). A :class:`PrefixAffinity` map keeps multi-turn
   ``/execute`` agent loops — whose next prompt extends the previous
   prompt + completion — on the replica already holding their KV prefix
   (SGLang's cache-aware front scheduler, approximated with an LRU of
   ``(prefix_len, crc32)`` keys instead of a radix tree).
2. **Hedged re-dispatch** — when the chosen replica produces no event
   within ``FLEET_HEDGE_MS``, the same request (same seed, same resume
   prefix) is dispatched to a second replica and whichever branch yields
   first wins; the loser is cancelled. Per-request seeded sampling makes
   the two transcripts identical, so winner choice can never change
   client-visible bytes.
3. **Cross-replica migration** — the fleet-level reset-and-replay. Each
   request's recoverable state is the portable (prompt, generated-prefix
   ids, seed) tuple (protocol.RequestExport, kept live by the engine
   scheduler). When a replica fails mid-request — engine stopped, reset
   budget exhausted, watchdog trip, scheduler death past recovery — the
   request is re-submitted to a healthy replica with ``resume_ids``: the
   engine re-splices prompt + prefix via one prefill (the PR 5 replay
   path) and the continuation is bit-identical. The relay suppresses the
   re-emitted prefix, so a client holding an open SSE stream sees a
   seamless byte-identical continuation. Engines without resume support
   simply replay from scratch under the same seed (same bytes, more
   compute) — the suppression logic is identical either way.
4. **Zero-downtime drains** — ``drain(replica)`` takes a replica out of
   rotation, nudges its in-flight requests to migrate (voluntarily, via
   the same path as crash failover), waits them out, and stops the
   engine; ``rejoin(replica)`` restarts it with a clean breaker. An
   ejected-then-rejoined replica cycles without dropping a request —
   the k8s rolling-restart story (process SIGTERM still drains the whole
   fleet through ``stop(drain_secs)``, server/__main__.py).

The fleet is deliberately an *engine*, not a service: everything above
the Engine seam (breaker, cache, middleware) works unchanged, and the
service-level breaker stays the outer ring for fleet-wide failures.
"""

from __future__ import annotations

import asyncio
import dataclasses
import inspect
import logging
import time
import zlib
from collections import OrderedDict, deque
from typing import AsyncIterator, Dict, List, Optional, Sequence, Set, Tuple

from ..obs import ledger as obs_ledger
from ..obs import slo as obs_slo
from ..obs import steptime as obs_steptime
from ..obs.ledger import CLASS_HEDGE_LOSER, GoodputLedger
from ..obs.trace import current_trace
from ..server.breaker import OPEN, CircuitBreaker
from .protocol import (EngineOverloaded, EngineResult, EngineUnavailable,
                       GenerationTimeout, RequestExport, RequestQuarantined)
from .qos import LANE_INTERACTIVE, LANES, current_qos, lane_rank

logger = logging.getLogger(__name__)

#: replica lifecycle states (the /health + metrics label set — fixed here
#: so cardinality is bounded by construction).
REPLICA_ACTIVE = "active"
REPLICA_DRAINING = "draining"
REPLICA_EJECTED = "ejected"
REPLICA_STATES = (REPLICA_ACTIVE, REPLICA_DRAINING, REPLICA_EJECTED)


class PrefixAffinity:
    """Prefix-keyed session affinity for multi-turn agent loops.

    A turn-N prompt in the ``/execute`` agent loop is turn N-1's prompt
    plus its completion plus the new user turn — a pure prefix
    extension. Full radix-tree matching (SGLang) is overkill for a
    router hint, so entries are ``(prefix_len, crc32(prefix)) →
    replica`` in an LRU: recorded at dispatch (the prompt itself) and at
    completion (prompt + generated text, the KV the replica now holds);
    lookup probes the recorded lengths ≤ ``len(prompt)`` longest-first
    and returns the first replica whose recorded prefix matches. False
    positives need a crc32 collision at equal length — harmless (a
    mis-routed request still serves correctly, it just misses the warm
    prefix)."""

    def __init__(self, maxsize: int = 2048, max_probe: int = 16):
        self.maxsize = maxsize
        self.max_probe = max_probe
        self._map: "OrderedDict[Tuple[int, int], int]" = OrderedDict()
        self._lengths: Dict[int, int] = {}   # refcount per recorded length

    @staticmethod
    def _crc(text: str) -> int:
        return zlib.crc32(text.encode("utf-8", "surrogatepass"))

    def record(self, text: str, replica: int) -> None:
        if not text:
            return
        key = (len(text), self._crc(text))
        if key not in self._map:
            self._lengths[len(text)] = self._lengths.get(len(text), 0) + 1
        self._map[key] = replica
        self._map.move_to_end(key)
        while len(self._map) > self.maxsize:
            (length, _), _ = self._map.popitem(last=False)
            n = self._lengths.get(length, 0) - 1
            if n <= 0:
                self._lengths.pop(length, None)
            else:
                self._lengths[length] = n

    def lookup(self, text: str) -> Optional[int]:
        """Replica that holds the longest recorded prefix of ``text``."""
        lengths = sorted((ln for ln in self._lengths if ln <= len(text)),
                         reverse=True)[:self.max_probe]
        for ln in lengths:
            key = (ln, self._crc(text[:ln]))
            rep = self._map.get(key)
            if rep is not None:
                self._map.move_to_end(key)
                return rep
        return None

    def forget_replica(self, replica: int) -> None:
        """Drop every entry pointing at ``replica`` (its KV is gone —
        ejected/drained replicas must not keep attracting sessions)."""
        dead = [k for k, v in self._map.items() if v == replica]
        for key in dead:
            del self._map[key]
            n = self._lengths.get(key[0], 0) - 1
            if n <= 0:
                self._lengths.pop(key[0], None)
            else:
                self._lengths[key[0]] = n


@dataclasses.dataclass(eq=False)   # identity hash: flights live in sets
class _Flight:
    """One in-flight fleet request, registered with the replica serving
    it so ``drain()`` can nudge it to migrate. ``lane`` (QoS ring) lets
    drains evict background work first and the router count only the
    occupancy a given lane actually contends with."""

    migrate: asyncio.Event = dataclasses.field(default_factory=asyncio.Event)
    lane: str = LANE_INTERACTIVE


class _Replica:
    """One engine replica + its routing signals."""

    def __init__(self, idx: int, engine, breaker: CircuitBreaker):
        self.idx = idx
        self.engine = engine
        self.state = REPLICA_ACTIVE
        self.breaker = breaker
        self.inflight = 0            # fleet relays currently dispatched here
        self.flights: Set[_Flight] = set()
        self.eject_cause: Optional[str] = None
        self.last_error: str = ""
        self.migrations_out = 0      # requests migrated OFF this replica
        self.dispatches = 0          # cumulative fleet dispatches landed
        self.not_ready_since: Optional[float] = None

    def weights_version(self) -> str:
        """The checkpoint version this replica's engine serves (ISSUE
        13) — the pin key for migration/hedge/replay routing and the
        per-replica /health stamp. "" for engines without versioning."""
        return str(getattr(self.engine, "weights_version", "") or "")

    def occupancy(self) -> int:
        """Cheap slot occupancy (never calls stats() — stats drains the
        fetch-latency samples owed to the /metrics scrape)."""
        slots = getattr(self.engine, "_slots", None)
        if slots:
            return sum(s is not None for s in slots)
        return self.inflight

    def occupancy_for(self, lane: Optional[str]) -> int:
        """Lane-aware occupancy (QoS ring): slots a request at ``lane``
        actually contends with — lower-lane slots are preemptible, so a
        replica full of background work is still routable for
        interactive traffic."""
        fn = getattr(self.engine, "lane_occupancy", None)
        if lane is None or not callable(fn):
            return self.occupancy()
        rank = lane_rank(lane)
        return sum(n for la, n in fn().items() if lane_rank(la) >= rank)

    def inflight_for(self, lane: Optional[str]) -> int:
        """Fleet relays dispatched here at or above ``lane``."""
        if lane is None:
            return self.inflight
        rank = lane_rank(lane)
        return sum(1 for f in self.flights
                   if lane_rank(getattr(f, "lane", LANE_INTERACTIVE))
                   >= rank)


class EngineFleet:
    """N engine replicas behind one Engine-protocol facade."""

    name = "fleet"

    #: monitor poll interval and how long a replica must read not-ready
    #: before ejection (debounces the watchdog's transient re-arm).
    MONITOR_INTERVAL = 0.05
    EJECT_GRACE_SECS = 0.2
    #: affinity is honoured unless the preferred replica is this many
    #: in-flight requests busier than the least-loaded candidate —
    #: cache locality is worth a little imbalance, not a hot spot.
    AFFINITY_SLACK = 4

    #: drain-rate freshness horizon for retry_after_hint (same semantics
    #: as the batcher's).
    DRAIN_RATE_HORIZON_SECS = 60.0

    def __init__(self, replicas: Sequence, *,
                 hedge_ms: float = 0.0,
                 affinity: bool = True,
                 migration_budget: int = 3,
                 rejoin_secs: float = 0.0,
                 drain_secs: float = 10.0,
                 breaker_threshold: int = 5,
                 breaker_window_secs: float = 30.0,
                 breaker_recovery_secs: float = 15.0):
        if not replicas:
            raise ValueError("fleet needs at least one replica")
        self.hedge_ms = max(0.0, hedge_ms)
        self.migration_budget = max(0, migration_budget)
        self.rejoin_secs = max(0.0, rejoin_secs)
        self.drain_secs_default = max(0.0, drain_secs)
        self._breaker_kw = dict(threshold=breaker_threshold,
                                window_secs=breaker_window_secs,
                                recovery_secs=breaker_recovery_secs)
        self.replicas: List[_Replica] = [
            _Replica(i, eng, CircuitBreaker(**self._breaker_kw))
            for i, eng in enumerate(replicas)
        ]
        self.affinity: Optional[PrefixAffinity] = (
            PrefixAffinity() if affinity else None)
        # Weight rollout (ISSUE 13): while a canary is set, the router
        # steers a bounded fraction of FRESH traffic at it via a share
        # accumulator (exact over any request count, no RNG); while a
        # swap is in flight (swap_hint > 0) a no-replica moment sheds
        # with a priced 503 instead of a bare EngineUnavailable.
        self._canary_idx: Optional[int] = None
        self._canary_share = 0.0
        self._canary_acc = 0.0
        self.swap_hint = 0.0
        self._stopping = False
        self._monitor_task: Optional[asyncio.Task] = None
        self._rejoin_tasks: Set[asyncio.Task] = set()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._reset_listener = None
        # Fleet counters (cumulative; /metrics delta-mirrors them).
        self._migrations = 0
        self._migrated_tokens = 0
        self._hedges = 0
        self._hedge_wins = 0
        self._drains = 0
        self._ejects = 0
        self._rejoins = 0
        self._finish_times: deque = deque(maxlen=128)
        # Goodput ledger (ISSUE 8): the fleet's OWN ledger holds the one
        # class only the relay can see — hedge_loser steps, billed when
        # a losing branch is cancelled. Replica engines bill everything
        # else; ledger_snapshot()/stats() merge all of them.
        self.ledger = GoodputLedger()
        # Inner ring → fleet ring: each replica supervisor's resets feed
        # that replica's breaker (a flapping replica leaves rotation even
        # while its own containment keeps recovering requests) and are
        # forwarded to the service listener for the outer breaker.
        for rep in self.replicas:
            hook = getattr(rep.engine, "set_reset_listener", None)
            if callable(hook):
                hook(self._make_reset_hook(rep))

    def _make_reset_hook(self, rep: _Replica):
        def on_reset(cause: str, _rep=rep) -> None:
            self._on_replica_reset(_rep, cause)
        return on_reset

    def _on_replica_reset(self, rep: _Replica, cause: str) -> None:
        """Called from the replica's scheduler thread after each engine
        reset: marshal onto the event loop (breaker transitions are
        loop-only by design) and forward to the service layer."""
        loop = self._loop
        if loop is not None and not loop.is_closed():
            loop.call_soon_threadsafe(rep.breaker.record_failure)
        else:  # pragma: no cover - pre-traffic reset
            rep.breaker.record_failure()
        listener = self._reset_listener
        if listener is not None:
            try:
                listener(cause)
            except Exception:  # pragma: no cover - listener is best-effort
                pass

    def set_reset_listener(self, fn) -> None:
        """Service-layer hook (the PR 1 breaker): fleet aggregation of
        every replica's reset stream."""
        self._reset_listener = fn

    # ----------------------------------------------------------- lifecycle

    @property
    def ready(self) -> bool:
        return (not self._stopping
                and any(rep.state == REPLICA_ACTIVE
                        and getattr(rep.engine, "ready", False)
                        for rep in self.replicas))

    async def start(self) -> None:
        self._stopping = False
        self._loop = asyncio.get_running_loop()
        results = await asyncio.gather(
            *(rep.engine.start() for rep in self.replicas),
            return_exceptions=True)
        failures = []
        for rep, res in zip(self.replicas, results):
            if isinstance(res, BaseException):
                rep.state = REPLICA_EJECTED
                rep.eject_cause = "start_failed"
                rep.last_error = f"{type(res).__name__}: {res}"
                failures.append((rep.idx, res))
                logger.error("fleet: replica %d failed to start: %s",
                             rep.idx, res)
        if len(failures) == len(self.replicas):
            raise failures[0][1]
        if failures:
            logger.warning("fleet: serving with %d/%d replicas",
                           len(self.replicas) - len(failures),
                           len(self.replicas))
        self._monitor_task = asyncio.create_task(self._monitor())

    async def stop(self, drain_secs: float = 0.0) -> None:
        """Whole-fleet shutdown (process SIGTERM): every replica drains
        in place — in-flight requests FINISH where they run (migrating
        between two dying replicas would be churn, not progress) while
        new submissions 503 so the LB drains us."""
        self._stopping = True
        if self._monitor_task is not None:
            self._monitor_task.cancel()
            try:
                await self._monitor_task
            except (asyncio.CancelledError, Exception):
                pass
            self._monitor_task = None
        for t in list(self._rejoin_tasks):
            t.cancel()
        self._rejoin_tasks.clear()
        await asyncio.gather(
            *(rep.engine.stop(drain_secs=drain_secs)
              for rep in self.replicas),
            return_exceptions=True)

    async def _monitor(self) -> None:
        """Replica-death detection: an active replica whose engine reads
        not-ready past a short grace (watchdog trip, reset budget
        exhausted, scheduler dead terminally) is ejected from rotation;
        its in-flight requests migrate via the per-request relay. With
        ``FLEET_REJOIN_SECS`` set, a restart is attempted after that
        delay (crash-looping replicas stay ejected — each rejoin needs a
        successful engine start)."""
        while True:
            await asyncio.sleep(self.MONITOR_INTERVAL)
            now = time.monotonic()
            for rep in self.replicas:
                if rep.state != REPLICA_ACTIVE:
                    continue
                if getattr(rep.engine, "ready", False):
                    rep.not_ready_since = None
                    continue
                if rep.not_ready_since is None:
                    rep.not_ready_since = now
                    continue
                if now - rep.not_ready_since >= self.EJECT_GRACE_SECS:
                    # Fleet escalation of the containment policy: an
                    # engine whose supervisor recently DENIED a reset
                    # (budget spent — it stopped recovering by design)
                    # gets an attributable eject cause; operators treat
                    # "reset_budget_exhausted" as replace-the-replica,
                    # not a transient flap.
                    cause = "not_ready"
                    sup = getattr(rep.engine, "supervisor", None)
                    denial = getattr(sup, "last_denial_wall", None)
                    if denial and time.time() - denial < 120.0:
                        cause = "reset_budget_exhausted"
                    self.eject(rep.idx, cause=cause)
                    if self.rejoin_secs > 0:
                        task = asyncio.create_task(self._auto_rejoin(rep))
                        self._rejoin_tasks.add(task)
                        task.add_done_callback(self._rejoin_tasks.discard)

    async def _auto_rejoin(self, rep: _Replica) -> None:
        await asyncio.sleep(self.rejoin_secs)
        try:
            await self.rejoin(rep.idx)
        except Exception as e:  # pragma: no cover - engine-dependent
            rep.last_error = f"rejoin failed: {e}"
            logger.exception("fleet: replica %d rejoin failed", rep.idx)

    def eject(self, idx: int, cause: str = "manual") -> None:
        """Take a replica out of rotation NOW. In-flight requests are
        nudged to migrate; queued routing never picks it again until
        ``rejoin``."""
        rep = self.replicas[idx]
        if rep.state == REPLICA_EJECTED:
            return
        rep.state = REPLICA_EJECTED
        rep.eject_cause = cause
        rep.not_ready_since = None
        self._ejects += 1
        if self.affinity is not None:
            self.affinity.forget_replica(idx)
        logger.warning("fleet: replica %d ejected (%s); %d in-flight "
                       "request(s) migrating", idx, cause, len(rep.flights))
        # Lowest lane first (QoS): on a crash-eject everyone migrates
        # this tick anyway, but the ordering keeps background's
        # re-splice load ahead of interactive's on the receiving side.
        for flight in sorted(
                rep.flights,
                key=lambda f: lane_rank(getattr(f, "lane", None))):
            flight.migrate.set()

    async def drain(self, idx: int,
                    drain_secs: Optional[float] = None) -> None:
        """Zero-downtime voluntary drain of one replica: out of rotation,
        in-flight requests migrate to healthy replicas (same re-splice
        path as crash failover — nothing waits for generations to end),
        then the engine stops. Pair with ``rejoin`` for a rolling
        restart that drops nothing."""
        rep = self.replicas[idx]
        drain_secs = (self.drain_secs_default if drain_secs is None
                      else max(0.0, drain_secs))
        if rep.state == REPLICA_ACTIVE:
            rep.state = REPLICA_DRAINING
            self._drains += 1
            if self.affinity is not None:
                self.affinity.forget_replica(idx)
        logger.info("fleet: draining replica %d (%d in-flight)",
                    idx, len(rep.flights))
        # Version-pinned migration (ISSUE 13): a nudged flight can only
        # re-splice onto a replica serving the SAME weights — so the
        # nudge targets are same-version siblings, and when none exist
        # (last replica, or last replica on the outgoing version during
        # a rollout promote) in-flight work finishes in place instead of
        # being aborted into unroutable migrations.
        v = rep.weights_version()
        targets = [r for r in self._routable()
                   if not v or r.weights_version() == v]
        if targets:
            # QoS eviction order: background (and batch) migrate FIRST;
            # interactive flights keep decoding here until the lower
            # lanes have re-seated (or a slice of the budget passes) so
            # the sibling absorbs the bulk re-splices before the
            # latency-sensitive ones arrive.
            lower = [f for f in rep.flights
                     if lane_rank(getattr(f, "lane", None))
                     < lane_rank(LANE_INTERACTIVE)]
            for flight in sorted(
                    lower, key=lambda f: lane_rank(getattr(f, "lane",
                                                           None))):
                flight.migrate.set()
            if lower:
                stage_deadline = time.monotonic() + min(
                    1.0, drain_secs * 0.25)
                while (any(f in rep.flights for f in lower)
                       and time.monotonic() < stage_deadline):
                    await asyncio.sleep(0.01)
            for flight in list(rep.flights):
                flight.migrate.set()
        elif rep.flights:
            # No same-version migration target (last routable replica,
            # or the last replica on this weights version): a nudge
            # would abort every in-flight request into "no healthy
            # replica" errors. Let them finish in place on this replica
            # within the drain budget instead — same finish-in-place
            # semantics as whole-fleet stop().
            logger.warning(
                "fleet: no same-version migration target while draining "
                "replica %d; letting %d in-flight requests finish in "
                "place", idx, len(rep.flights))
        deadline = time.monotonic() + drain_secs
        while rep.flights and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        await rep.engine.stop(
            drain_secs=max(0.0, deadline - time.monotonic()))
        rep.state = REPLICA_EJECTED
        rep.eject_cause = "drain"

    async def rejoin(self, idx: int) -> None:
        """Restart an ejected/drained replica and return it to rotation
        with a clean breaker."""
        rep = self.replicas[idx]
        if rep.state == REPLICA_ACTIVE and getattr(rep.engine, "ready",
                                                   False):
            # Genuinely healthy — nothing to do. A replica whose engine
            # was hard-killed but which the monitor has not yet ejected
            # (debounce) is still state=active with ready=False: the
            # early return used to skip the restart entirely there,
            # leaving a dead engine "active" until the monitor caught
            # up — a rejoin racing the eject must restart it anyway.
            return
        if not getattr(rep.engine, "ready", False):
            try:
                # Idempotent cleanup for engines ejected mid-flight
                # (watchdog/reset-budget paths leave threads behind).
                await rep.engine.stop()
            except Exception:  # pragma: no cover - engine-dependent
                pass
            await rep.engine.start()
        rep.breaker = CircuitBreaker(**self._breaker_kw)
        rep.state = REPLICA_ACTIVE
        rep.eject_cause = None
        rep.not_ready_since = None
        rep.last_error = ""
        self._rejoins += 1
        logger.info("fleet: replica %d rejoined", idx)

    # -------------------------------------------- weight rollout (ISSUE 13)

    @property
    def weights_version(self) -> str:
        """The STABLE version the fleet serves: the most common version
        among active non-canary replicas (falling back to any replica)
        — what /health's top level and X-Model-Version echo."""
        counts: Dict[str, int] = {}
        for rep in self.replicas:
            if rep.state == REPLICA_ACTIVE and rep.idx != self._canary_idx:
                v = rep.weights_version()
                if v:
                    counts[v] = counts.get(v, 0) + 1
        if not counts:
            for rep in self.replicas:
                v = rep.weights_version()
                if v:
                    counts[v] = counts.get(v, 0) + 1
        if not counts:
            return ""
        return max(sorted(counts), key=lambda v: counts[v])

    def set_canary(self, idx: int, share: float) -> None:
        """Steer ``share`` of fresh traffic at replica ``idx`` (the
        rollout controller's observe phase). Clamped to at most half —
        the canary must never be able to starve the stable cohort's
        interactive lane."""
        self._canary_idx = int(idx)
        self._canary_share = min(max(0.0, float(share)), 0.5)
        self._canary_acc = 0.0

    def clear_canary(self) -> None:
        self._canary_idx = None
        self._canary_share = 0.0
        self._canary_acc = 0.0

    # ------------------------------------------------------------- routing

    def _routable(self, exclude: Sequence[int] = ()) -> List[_Replica]:
        return [
            rep for rep in self.replicas
            if rep.idx not in exclude
            and rep.state == REPLICA_ACTIVE
            and getattr(rep.engine, "ready", False)
            and rep.breaker.state != OPEN
        ]

    def _route(self, prompt: str, exclude: Sequence[int] = (),
               lane: Optional[str] = None,
               version: Optional[str] = None) -> Optional[_Replica]:
        """Health-aware pick: least-loaded among routable replicas,
        overridden by prefix affinity unless the preferred replica is
        more than AFFINITY_SLACK requests busier. With ``lane`` set the
        load keys are lane-aware (QoS ring): only in-flight work at or
        above the request's lane counts, so a replica whose slots are
        all preemptible background work routes like an idle one for
        interactive traffic.

        Weight rollout (ISSUE 13): ``version`` pins the pick to
        replicas serving exactly that checkpoint — an established
        stream's re-splice cannot be byte-identical across weights, so
        a version-mismatched candidate is simply not a candidate (None
        when no same-version replica is routable; the caller decides
        what that means). Fresh traffic (``version=None``) is subject
        to canary steering instead: the share accumulator sends the
        canary its bounded fraction and keeps the rest on the stable
        cohort."""
        cands = self._routable(exclude)
        if not cands:
            return None
        if version is not None:
            cands = [r for r in cands if r.weights_version() == version]
            if not cands:
                return None
        elif self._canary_idx is not None:
            canary = next((r for r in cands
                           if r.idx == self._canary_idx), None)
            others = [r for r in cands if r.idx != self._canary_idx]
            if canary is not None and others:
                self._canary_acc += self._canary_share
                if self._canary_acc >= 1.0:
                    self._canary_acc -= 1.0
                    if self.affinity is not None:
                        self.affinity.record(prompt, canary.idx)
                    return canary
                # Stable traffic stays off the canary — without this the
                # canary's least-loaded idleness would attract far more
                # than its bounded share.
                cands = others
            # canary-only candidates: availability beats the share bound.
        best = min(cands, key=lambda r: (r.inflight_for(lane),
                                         r.occupancy_for(lane),
                                         r.inflight, r.idx))
        if self.affinity is not None:
            want = self.affinity.lookup(prompt)
            if want is not None and want != best.idx:
                for rep in cands:
                    if (rep.idx == want
                            and rep.inflight
                            <= best.inflight + self.AFFINITY_SLACK):
                        best = rep
                        break
            self.affinity.record(prompt, best.idx)
        return best

    # --------------------------------------------------------------- relay

    async def _replica_events(self, rep: _Replica, *, prompt: str,
                              max_tokens: int, temperature: float,
                              timeout: Optional[float], seed: int,
                              resume_ids: Optional[List[int]],
                              export: RequestExport):
        """One dispatch on one replica, normalized to (event, payload).

        Engines exposing ``stream_events`` (the chunked schedulers) get
        the full contract — seed pinning, resume import, live export.
        Anything else speaking only the base Engine protocol is driven
        through ``generate`` (full EngineResult fidelity; its text
        arrives as one token event and migration replays from scratch —
        prefix suppression keeps the client bytes identical)."""
        fn = getattr(rep.engine, "stream_events", None)
        if fn is not None:
            async for ev in fn(prompt, max_tokens=max_tokens,
                               temperature=temperature, timeout=timeout,
                               seed=seed, resume_ids=resume_ids,
                               export=export):
                yield ev
            return
        kw = dict(max_tokens=max_tokens, temperature=temperature,
                  timeout=timeout)
        try:
            # Pin the fleet-minted seed when the engine supports it —
            # hedge races and replay-from-scratch migrations depend on
            # two dispatches producing the SAME bytes. (Base-protocol
            # engines without a seed param are rule-deterministic.)
            if "seed" in inspect.signature(rep.engine.generate).parameters:
                kw["seed"] = seed
        except (TypeError, ValueError):  # pragma: no cover - exotic impls
            pass
        result = await rep.engine.generate(prompt, **kw)
        if result.text:
            yield ("token", result.text)
        yield ("done", result)

    async def _pump(self, tag: int, rep: _Replica, q: asyncio.Queue,
                    **kw) -> None:
        """Drive one branch's event stream into the shared queue. Errors
        travel in-band; cancellation closes the engine generator (which
        aborts the slot — the engine's documented disconnect path)."""
        try:
            async for ev in self._replica_events(rep, **kw):
                q.put_nowait((tag, "ev", ev))
            q.put_nowait((tag, "end", None))
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            q.put_nowait((tag, "err", e))

    @staticmethod
    def _is_migratable(e: BaseException) -> bool:
        """Replica-infrastructure failures migrate; request-level
        verdicts don't. Quarantine is terminal BY DESIGN (a poisonous
        request re-splice would just poison the next replica); timeouts
        are the request's own deadline; overload is handled separately
        (reroute, not migration)."""
        if isinstance(e, (RequestQuarantined, GenerationTimeout,
                          EngineOverloaded)):
            return False
        return isinstance(e, EngineUnavailable)

    async def _stream_events(self, prompt: str, *, max_tokens: int = 128,
                             temperature: float = 0.0,
                             timeout: Optional[float] = None,
                             seed: Optional[int] = None):
        """The fleet relay: route → dispatch (hedged) → re-emit events,
        migrating across replicas on infrastructure failure or drain
        nudge with the already-delivered prefix suppressed."""
        if self._stopping:
            raise EngineUnavailable("fleet stopping")
        if seed is None:
            seed = zlib.crc32(
                prompt.encode("utf-8", "surrogatepass")) & 0x7FFFFFFF
        seed = int(seed) & 0x7FFFFFFF
        deadline = (time.monotonic() + timeout) if timeout else None
        trace = current_trace()
        # QoS lane rides the same contextvar the engines read; the fleet
        # uses it for lane-aware routing and drain-eviction ordering.
        qctx = current_qos()
        flight = _Flight(lane=(qctx.lane if qctx is not None
                               and qctx.lane in LANES
                               else LANE_INTERACTIVE))
        delivered = ""               # text already yielded to the caller
        export_ids: List[int] = []   # best-known generated prefix (ids)
        migrations = 0
        exclude: List[int] = []
        last_err: Optional[BaseException] = None
        overload_tried: List[int] = []
        # Weight rollout (ISSUE 13): the checkpoint version that
        # generated this stream's prefix. An ESTABLISHED stream (any
        # generated ids or delivered bytes) only routes to same-version
        # replicas — a cross-version re-splice cannot be byte-identical
        # — while a fresh request routes freely and, after a failed
        # fresh dispatch, replays from scratch on whatever version it
        # lands on (pin re-stamps per attempt).
        pinned: Optional[str] = None

        while True:
            established = bool(delivered) or bool(export_ids)
            want = pinned if (pinned and established) else None
            rep = self._route(prompt, exclude=exclude + overload_tried,
                              lane=flight.lane, version=want)
            if rep is None:
                if isinstance(last_err, EngineOverloaded):
                    # Every routable replica shed: propagate, re-priced
                    # from the FLEET-wide drain rate (a single replica's
                    # estimate undersells N replicas draining). The
                    # CLASS is preserved — a per-tenant 429
                    # (TenantOverloaded) must stay a 429 through the
                    # fleet, not dilute into everyone's 503.
                    raise type(last_err)(
                        str(last_err),
                        retry_after=self.retry_after_hint())
                if want is not None and self._routable():
                    # Healthy replicas exist — on OTHER weights. Failing
                    # here is the version-pinning contract: the client
                    # keeps the bytes it has; a cross-version splice
                    # would silently corrupt the transcript. The
                    # explicit error names the contract (chained on the
                    # root cause) so operators see "rollout pinning",
                    # not a bare replica error.
                    raise EngineUnavailable(
                        f"no replica serves weights {want} for this "
                        f"established stream (rollout in progress)"
                    ) from last_err
                if self.swap_hint > 0:
                    # A rollout swap is mid-flight on the only capacity
                    # (FLEET_SIZE=1 in-place swap): shed with a priced
                    # Retry-After so the LB re-offers after the warmup.
                    raise EngineOverloaded(
                        "no replica available while a weight swap is "
                        "in flight", retry_after=self.swap_hint)
                raise last_err or EngineUnavailable(
                    "no healthy replica available")
            if not established:
                # Fresh (re-)dispatch: (re-)pin to the replica actually
                # serving it — a failed fresh attempt on v1 may replay
                # from scratch on v2 as a fresh request.
                pinned = rep.weights_version() or None
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise GenerationTimeout("generation timeout")
            # Between attempts the flight is registered in NO replica's
            # flights set, so a set migrate event here is necessarily a
            # stale nudge from the attempt that just ended (the monitor's
            # eject races the engine error when a replica dies) — clear
            # it, or the fresh dispatch would abort as a spurious second
            # migration and double-spend the budget.
            flight.migrate.clear()
            outcome = payload = None
            async for item in self._attempt_events(
                    rep, flight,
                    prompt=prompt, max_tokens=max_tokens,
                    temperature=temperature, timeout=remaining, seed=seed,
                    resume_ids=(list(export_ids) if migrations else None),
                    delivered=delivered,
                    version=rep.weights_version() or None):
                kind = item[0]
                if kind == "token":
                    delivered += item[1]
                    yield ("token", item[1])
                else:
                    outcome, payload = kind, item[1:]
            if outcome is None:  # pragma: no cover - defensive
                outcome, payload = "err", (
                    EngineUnavailable("attempt ended without an outcome"),
                    [], None)
            if outcome == "done":
                result = payload[0]
                rep.breaker.record_success()
                self._finish_times.append(time.monotonic())
                if self.affinity is not None:
                    # The replica now holds KV for prompt + completion —
                    # the next agent turn extends exactly this prefix.
                    self.affinity.record(prompt + result.text, rep.idx)
                yield ("done", result)
                return
            if outcome == "migrate":
                # Voluntary (drain/eject nudge): no breaker failure.
                err, ids, ver = payload
                if len(ids) > len(export_ids):
                    export_ids = ids
                if export_ids and ver:
                    # The engine's own export stamp is authoritative
                    # for which weights generated the carried ids.
                    pinned = ver
                migrations = self._count_migration(
                    rep, export_ids, migrations, err)
                if trace is not None:
                    trace.event(
                        f"fleet: migrating off replica {rep.idx} "
                        f"({len(export_ids)} tokens carried, drain/eject)")
                    # Span link: the stitched timeline's replica handoff
                    # — the destination's admit events follow it.
                    trace.link("migrated", from_replica=rep.idx,
                               tokens=len(export_ids), cause="drain_eject",
                               weights_version=pinned or "")
                # Don't exclude by index: the nudged replica is already
                # unroutable by STATE (draining/ejected), and the nudge
                # may have hit a hedge branch — excluding the primary
                # here would blacklist the healthy replica serving us.
                exclude = []
                last_err = err
                continue
            # outcome == "err"
            err, ids, ver = payload
            if len(ids) > len(export_ids):
                export_ids = ids
            if export_ids and ver:
                pinned = ver
            if isinstance(err, EngineOverloaded):
                # Backpressure on ONE replica is a routing signal, not an
                # engine failure: try the others once each.
                overload_tried.append(rep.idx)
                last_err = err
                if trace is not None:
                    trace.event(f"fleet: replica {rep.idx} shed "
                                f"(overloaded); rerouting")
                continue
            if not self._is_migratable(err):
                raise err
            rep.last_error = f"{type(err).__name__}: {err}"
            rep.breaker.record_failure()
            migrations = self._count_migration(
                rep, export_ids, migrations, err)
            if trace is not None:
                trace.event(
                    f"fleet: replica {rep.idx} failed mid-request "
                    f"({type(err).__name__}); migrating with "
                    f"{len(export_ids)} generated tokens")
                trace.link("migrated", from_replica=rep.idx,
                           tokens=len(export_ids),
                           cause=type(err).__name__,
                           weights_version=pinned or "")
            logger.warning(
                "fleet: migrating request off replica %d after %s "
                "(%d generated tokens carried)", rep.idx,
                type(err).__name__, len(export_ids))
            exclude = [rep.idx]
            last_err = err

    def _count_migration(self, rep: _Replica, export_ids: List[int],
                         migrations: int,
                         err: Optional[BaseException]) -> int:
        """Shared bookkeeping for BOTH migration arms (voluntary
        drain/eject nudge and engine failure): the budget check comes
        FIRST — a budget-exceeded attempt is not a migration — then the
        fleet/replica counters."""
        migrations += 1
        if migrations > self.migration_budget:
            raise err or EngineUnavailable(
                "fleet migration budget exhausted")
        rep.migrations_out += 1
        self._migrations += 1
        self._migrated_tokens += len(export_ids)
        return migrations

    async def _attempt_events(self, rep: _Replica, flight: _Flight, *,
                              prompt: str, max_tokens: int,
                              temperature: float,
                              timeout: Optional[float], seed: int,
                              resume_ids: Optional[List[int]],
                              delivered: str,
                              version: Optional[str] = None):
        """One (possibly hedged) dispatch, yielded incrementally:

        - ``("token", piece)`` — continuation text past the
          already-delivered prefix (suppression applied here), streamed
          live as the winning branch produces it;
        - terminally ONE of ``("done", result)``, ``("migrate", err,
          ids)`` (drain/eject nudge), or ``("err", err, ids)`` — ``ids``
          is the best export snapshot for the caller's re-splice.
        """
        q: asyncio.Queue = asyncio.Queue()
        branches: List[dict] = []
        mig_task: Optional[asyncio.Task] = None
        pending_skip = len(delivered)
        hedge_armed = self.hedge_ms > 0

        def launch(target: _Replica) -> None:
            tag = len(branches)
            export = RequestExport(ids=list(resume_ids or []))
            target.inflight += 1
            target.dispatches += 1
            target.flights.add(flight)
            task = asyncio.create_task(self._pump(
                tag, target, q,
                prompt=prompt, max_tokens=max_tokens,
                temperature=temperature, timeout=timeout, seed=seed,
                resume_ids=resume_ids, export=export))
            branches.append({"rep": target, "export": export,
                             "task": task, "dead": False})

        async def close_branch(b: dict) -> None:
            if not b["task"].done():
                b["task"].cancel()
                try:
                    await b["task"]
                except (asyncio.CancelledError, Exception):
                    pass
            if not b.get("closed"):
                b["closed"] = True
                b["rep"].inflight -= 1
                b["rep"].flights.discard(flight)

        def best_ids() -> List[int]:
            return list(max((b["export"].ids for b in branches), key=len))

        def best_version() -> Optional[str]:
            """The ENGINE's own stamp of which weights generated the
            best export's ids (set at submit) — what the caller's
            version pin routes on. None for base-protocol engines that
            never see the export."""
            e = max((b["export"] for b in branches),
                    key=lambda ex: len(ex.ids))
            return e.weights_version or None

        def bill_loser(b: dict, cause: str) -> None:
            """Flight recorder + goodput ledger for a losing hedge
            branch. The BILLING itself happens engine-side: the
            export's ``discard`` flag (set before the cancel) makes the
            loser replica's finish path classify its emitted tokens as
            hedge_loser instead of delivered — the engine knows the
            request's tenant and would otherwise bill the same steps as
            goodput the client never received. The fleet only bills its
            own ledger for engines with no ledger at all, and leaves
            the span link (with the cancel cause) so the loser no
            longer vanishes from /debug/requests."""
            if b.get("loser_billed"):
                return
            b["loser_billed"] = True
            lost = len(b["export"].ids) - len(resume_ids or [])
            if lost > 0 and getattr(b["rep"].engine, "ledger",
                                    None) is None:
                self.ledger.record(CLASS_HEDGE_LOSER, lost,
                                   lane=flight.lane)
            trace = current_trace()
            if trace is not None:
                trace.link("hedge_loser", replica=b["rep"].idx,
                           tokens=max(0, lost), cause=cause)

        launch(rep)
        winner: Optional[int] = None
        try:
            if flight.migrate.is_set():
                yield ("migrate", None, list(resume_ids or []), None)
                return
            mig_task = asyncio.create_task(self._migrate_sentinel(flight, q))
            while True:
                try:
                    if hedge_armed and winner is None:
                        item = await asyncio.wait_for(
                            q.get(), self.hedge_ms / 1000.0)
                    else:
                        item = await q.get()
                except asyncio.TimeoutError:
                    # Hedge budget blown with no event yet: dispatch the
                    # same request (same seed/resume — identical bytes)
                    # to a second replica and race the branches.
                    hedge_armed = False
                    # Same-version only (ISSUE 13): the hedge's whole
                    # contract is that both branches produce identical
                    # bytes, which only holds on identical weights.
                    alt = self._route(
                        prompt, exclude=[b["rep"].idx for b in branches],
                        lane=flight.lane, version=version)
                    if alt is not None:
                        self._hedges += 1
                        trace = current_trace()
                        if trace is not None:
                            trace.event(
                                f"fleet: hedging onto replica {alt.idx} "
                                f"(no event within {self.hedge_ms:.0f}ms "
                                f"from replica {rep.idx})")
                            trace.link("hedge", primary=rep.idx,
                                       hedge=alt.idx)
                        launch(alt)
                    continue
                tag, kind, val = item
                if kind == "migrate":
                    yield ("migrate", None, best_ids(), best_version())
                    return
                b = branches[tag]
                if winner is None and kind == "ev":
                    winner = tag
                    if tag != 0:
                        self._hedge_wins += 1
                    for j, other in enumerate(branches):
                        if j != tag:
                            # Flag BEFORE the cancel: the loser engine's
                            # abort-finish must see it and bill these
                            # tokens as hedge_loser, not delivered.
                            other["export"].discard = True
                            await close_branch(other)
                            bill_loser(other, "lost_race")
                if winner is not None and tag != winner:
                    continue
                if kind == "ev":
                    event, payload = val
                    if event == "token":
                        piece = payload
                        if pending_skip:
                            cut = min(pending_skip, len(piece))
                            pending_skip -= cut
                            piece = piece[cut:]
                        if piece:
                            yield ("token", piece)
                    elif event == "done":
                        yield ("done", payload)
                        return
                elif kind == "end":
                    # Stream closed without a done event — an engine
                    # contract breach; treat as a replica failure, but
                    # (like the err arm) let a still-live hedge branch
                    # win instead of failing the whole attempt.
                    b["dead"] = True
                    if winner is None and any(
                            not ob["dead"] for ob in branches):
                        continue
                    yield ("err", EngineUnavailable(
                        "replica stream ended without a result"),
                        best_ids(), best_version())
                    return
                else:  # kind == "err"
                    b["dead"] = True
                    if winner is None and any(
                            not ob["dead"] for ob in branches):
                        # The primary died before any event but a hedge
                        # is still running — let it win.
                        continue
                    yield ("err", val, best_ids(), best_version())
                    return
        finally:
            if mig_task is not None:
                mig_task.cancel()
                try:
                    await mig_task
                except (asyncio.CancelledError, Exception):
                    pass
            for j, b in enumerate(branches):
                # A branch raced past the winner decision (or the caller
                # tore the attempt down mid-race): still a loser.
                if winner is not None and j != winner:
                    b["export"].discard = True
                await close_branch(b)
                if winner is not None and j != winner:
                    bill_loser(b, "cancelled")

    @staticmethod
    async def _migrate_sentinel(flight: _Flight, q: asyncio.Queue) -> None:
        await flight.migrate.wait()
        q.put_nowait((-1, "migrate", None))

    # ------------------------------------------------------------- serving

    async def generate(self, prompt: str, *, max_tokens: int = 128,
                       temperature: float = 0.0,
                       timeout: Optional[float] = None,
                       seed: Optional[int] = None) -> EngineResult:
        result: Optional[EngineResult] = None
        async for event, payload in self._stream_events(
                prompt, max_tokens=max_tokens, temperature=temperature,
                timeout=timeout, seed=seed):
            if event == "done":
                result = payload
        if result is None:  # pragma: no cover - defensive
            raise EngineUnavailable("fleet stream ended without a result")
        return result

    async def generate_stream(self, prompt: str, *, max_tokens: int = 128,
                              temperature: float = 0.0,
                              timeout: Optional[float] = None,
                              seed: Optional[int] = None
                              ) -> AsyncIterator[str]:
        async for event, payload in self._stream_events(
                prompt, max_tokens=max_tokens, temperature=temperature,
                timeout=timeout, seed=seed):
            if event == "token":
                yield payload

    # ------------------------------------------------------ observability

    def retry_after_hint(self, extra_depth: int = 0) -> float:
        """Retry-After priced from the FLEET-wide drain rate: total
        queued work across replicas over the fleet's recent completion
        rate — a shed must not quote one engine's estimate when N
        replicas are draining the backlog."""
        depth = extra_depth
        for rep in self.replicas:
            q = getattr(rep.engine, "_admissions", None)
            if q is not None:
                depth += q.qsize()
            else:
                depth += len(getattr(rep.engine, "_queue", ()))
        horizon = time.monotonic() - self.DRAIN_RATE_HORIZON_SECS
        ts = [t for t in list(self._finish_times) if t >= horizon]
        if len(ts) >= 2 and ts[-1] > ts[0]:
            rate = (len(ts) - 1) / (ts[-1] - ts[0])
            if rate > 0:
                return min(max(depth / rate, 1.0), 60.0)
        return 5.0

    def qos_health(self) -> dict:
        """Fleet rollup of the replicas' cheap QoS views (/health
        section): lane depths sum, brownout reports the worst replica,
        preemption/expiry counters sum."""
        agg: dict = {"lanes": {}, "brownout_level": 0,
                     "preemptions_total": 0, "preemptions_last_60s": 0,
                     "queue_expired_total": 0, "queue_displaced_total": 0}
        seen = False
        for rep in self.replicas:
            fn = getattr(rep.engine, "qos_health", None)
            if not callable(fn):
                continue
            try:
                q = fn() or {}
            except Exception:   # pragma: no cover - stopped replica
                continue
            seen = True
            for lane, n in (q.get("lanes") or {}).items():
                agg["lanes"][lane] = agg["lanes"].get(lane, 0) + n
            agg["brownout_level"] = max(agg["brownout_level"],
                                        q.get("brownout_level", 0))
            for k in ("preemptions_total", "preemptions_last_60s",
                      "queue_expired_total", "queue_displaced_total"):
                agg[k] += q.get(k, 0)
        return agg if seen else {}

    def kv_pool_health(self) -> dict:
        """Fleet rollup of the replicas' KV-pool views (ISSUE 10):
        block-state counts and sharing/COW/radix counters sum — each
        replica owns its own pool (block ids are engine-local), so the
        rollup is capacity accounting, not a shared address space."""
        agg: dict = {}
        radix: dict = {}
        host: dict = {}
        host_fails: dict = {}
        seen = radix_seen = host_seen = False
        for rep in self.replicas:
            fn = getattr(rep.engine, "kv_pool_health", None)
            if not callable(fn):
                continue
            try:
                p = fn() or None
            except Exception:   # pragma: no cover - stopped replica
                continue
            if not p:
                continue
            seen = True
            for k, v in p.items():
                if k == "radix":
                    if v:
                        radix_seen = True
                        for rk, rv in v.items():
                            # Budgets/counts sum; per-replica-identical
                            # config passes through below.
                            radix[rk] = radix.get(rk, 0) + rv
                elif k == "host_tier":
                    if v:
                        host_seen = True
                        for hk, hv in v.items():
                            if hk == "onload_fail_total":
                                for cause, n in (hv or {}).items():
                                    host_fails[cause] = (
                                        host_fails.get(cause, 0) + n)
                            else:
                                # capacity/used/free sum like the device
                                # tier's block counts: each replica owns
                                # its own host store, so the rollup is
                                # fleet-wide capacity accounting.
                                host[hk] = host.get(hk, 0) + hv
                elif k == "page":
                    # Config, identical per replica — pass through, a
                    # sum would triple the "tokens per block" math any
                    # consumer derives from the rollup.
                    agg[k] = v
                elif isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
        if not seen:
            return {}
        agg["radix"] = radix if radix_seen else None
        if host_seen:
            host["onload_fail_total"] = host_fails
            agg["host_tier"] = host
        return agg

    def sharding_health(self) -> dict:
        """Fleet view of the replicas' sharding config (ISSUE 14):
        replicas run one config, so the mesh/fraction/pool fields pass
        through from the first reporting replica; the loud-fallback
        flag is OR-ed — ANY replica silently serving the dense ladder
        under a requested pool must surface at the fleet level."""
        agg: dict = {}
        fallback = False
        draft_fallback = False
        for rep in self.replicas:
            fn = getattr(rep.engine, "sharding_health", None)
            if not callable(fn):
                continue
            try:
                s = fn() or None
            except Exception:   # pragma: no cover - stopped replica
                continue
            if not s:
                continue
            fallback = fallback or bool(s.get("kv_pool_mesh_fallback"))
            draft_fallback = (draft_fallback
                              or bool(s.get("draft_kv_fallback")))
            if not agg:
                agg = dict(s)
        if not agg:
            return {}
        agg["kv_pool_mesh_fallback"] = fallback
        # ISSUE 18: ANY replica serving the draft KV replicated (the
        # gather fallback) must surface at the fleet level, same rule
        # as the pool's loud fallback.
        agg["draft_kv_fallback"] = draft_fallback
        return agg

    def grammar_health(self) -> dict:
        """Fleet rollup of the replicas' grammar views (ISSUE 11):
        forced/masked/dead-end totals sum; the compiled-grammar
        identity (hash, profile, state/class counts) passes through —
        replicas run the same config, so their grammars are identical
        by construction."""
        agg: dict = {}
        dead: dict = {}
        seen = False
        for rep in self.replicas:
            fn = getattr(rep.engine, "grammar_health", None)
            if not callable(fn):
                continue
            try:
                g = fn() or None
            except Exception:   # pragma: no cover - stopped replica
                continue
            if not g:
                continue
            seen = True
            for k, v in g.items():
                if k == "dead_ends_total":
                    for ck, cv in (v or {}).items():
                        dead[ck] = dead.get(ck, 0) + cv
                elif k.endswith("_total") and isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
                else:
                    agg[k] = v
        if not seen:
            return {}
        agg["dead_ends_total"] = dead
        return agg

    def spec_health(self) -> dict:
        """Fleet rollup of the replicas' speculative-decode views
        (ISSUE 12): drafted/accepted/degraded totals sum, the
        acceptance ratio re-derives from the summed totals (ratios
        don't average), identity fields (draft model, k) pass through
        — replicas run one config — and ``active`` is AND-ed (one
        replica's dead draft shows as a fleet-level degradation)."""
        agg: dict = {}
        seen = False
        active = True
        draft_fallback = False
        for rep in self.replicas:
            fn = getattr(rep.engine, "spec_health", None)
            if not callable(fn):
                continue
            try:
                s = fn() or None
            except Exception:   # pragma: no cover - stopped replica
                continue
            if not s:
                continue
            seen = True
            active = active and bool(s.get("active"))
            draft_fallback = (draft_fallback
                              or bool(s.get("draft_kv_fallback")))
            for k, v in s.items():
                if k.endswith("_total") and isinstance(v, (int, float)):
                    agg[k] = agg.get(k, 0) + v
                elif k not in ("active", "acceptance_ratio",
                               "draft_kv_fallback"):
                    agg[k] = v
        if not seen:
            return {}
        agg["active"] = active
        agg["draft_kv_fallback"] = draft_fallback
        drafted = agg.get("drafted_tokens_total", 0)
        agg["acceptance_ratio"] = (
            round(agg.get("accepted_tokens_total", 0) / drafted, 4)
            if drafted else None)
        return agg

    def steptime_health(self) -> dict:
        """Fleet rollup of the replicas' step-time sentinel snapshots
        (ISSUE 15): per-key digests merge worst-replica percentiles,
        breaches union WITH replica attribution — a straggling replica
        is exactly a breach naming its index while its siblings' stay
        clean (obs/steptime.py merge_snapshots)."""
        snaps: List[Optional[dict]] = []
        seen = False
        for rep in self.replicas:
            fn = getattr(rep.engine, "steptime_health", None)
            s = None
            if callable(fn):
                try:
                    s = fn() or None
                except Exception:   # pragma: no cover - stopped replica
                    s = None
            seen = seen or bool(s)
            snaps.append(s)
        if not seen:
            return {}
        return obs_steptime.merge_snapshots(snaps)

    def slo_health(self) -> dict:
        """Fleet rollup of the replicas' SLO burn snapshots: per-window
        counts sum, burn rates recompute from the sums (rates don't
        average) — obs/slo.py merge_snapshots."""
        snaps = []
        for rep in self.replicas:
            fn = getattr(rep.engine, "slo_health", None)
            if not callable(fn):
                continue
            try:
                snaps.append(fn() or {})
            except Exception:   # pragma: no cover - stopped replica
                continue
        return obs_slo.merge_snapshots(snaps)

    def ledger_snapshot(self) -> dict:
        """Fleet goodput ledger for /debug/ledger: replica lane tables
        merged with the relay's own hedge-loser ledger, hashed-tenant
        tables summed, conservation re-checked on the merged books."""
        snaps, tenants, conserv = [], {}, []
        for rep in self.replicas:
            fn = getattr(rep.engine, "ledger_snapshot", None)
            if not callable(fn):
                continue
            try:
                s = fn() or {}
            except Exception:   # pragma: no cover - stopped replica
                continue
            for t, row in (s.pop("tenants", None) or {}).items():
                dst = tenants.setdefault(
                    t, {cls: 0 for cls in obs_ledger.LEDGER_CLASSES})
                for cls in obs_ledger.LEDGER_CLASSES:
                    dst[cls] += int(row.get(cls, 0))
            c = s.pop("conservation", None)
            if c:
                conserv.append(c)
            snaps.append(s)
        own = self.ledger.snapshot()
        for t, row in self.ledger.tenant_snapshot().items():
            dst = tenants.setdefault(
                t, {cls: 0 for cls in obs_ledger.LEDGER_CLASSES})
            for cls in obs_ledger.LEDGER_CLASSES:
                dst[cls] += int(row.get(cls, 0))
        conserv.append(self.ledger.conservation())
        snaps.append(own)
        merged = obs_ledger.merge_snapshots(snaps)
        merged["tenants"] = {
            t: obs_ledger.GoodputLedger._derive(row)
            for t, row in sorted(tenants.items())}
        total = sum(c.get("total_steps", 0) for c in conserv)
        accounted = sum(c.get("accounted", 0) for c in conserv)
        merged["conservation"] = {
            "total_steps": total,
            "accounted": accounted,
            "balanced": (accounted == total
                         and all(c.get("balanced") for c in conserv)),
        }
        return merged

    def fleet_health(self) -> dict:
        """Cheap per-replica health view for /health (never calls
        stats() — that drains metric samples owed to the scrape)."""
        reps = []
        last_wall = None
        last_cause = None
        for rep in self.replicas:
            sup = getattr(rep.engine, "supervisor", None)
            reset_iso = cause = None
            if sup is not None and sup.last_reset_wall:
                reset_iso = time.strftime(
                    "%Y-%m-%dT%H:%M:%S",
                    time.gmtime(sup.last_reset_wall)) + "Z"
                cause = sup.last_reset_cause
                if last_wall is None or sup.last_reset_wall > last_wall:
                    last_wall, last_cause = sup.last_reset_wall, cause
            reps.append({
                "replica": rep.idx,
                "state": rep.state,
                "engine_ready": bool(getattr(rep.engine, "ready", False)),
                "breaker": rep.breaker.state,
                "occupancy": rep.occupancy(),
                "inflight": rep.inflight,
                "dispatches": rep.dispatches,
                "migrations_out": rep.migrations_out,
                "weights_version": rep.weights_version() or None,
                "eject_cause": rep.eject_cause,
                "last_error": rep.last_error or None,
                "last_reset": reset_iso,
                "last_reset_cause": cause,
            })
        counts = {s: 0 for s in REPLICA_STATES}
        versions: Dict[str, int] = {}
        for rep in self.replicas:
            counts[rep.state] += 1
            v = rep.weights_version()
            if v:
                versions[v] = versions.get(v, 0) + 1
        return {
            "size": len(self.replicas),
            # Weight rollout (ISSUE 13): which checkpoint each replica
            # serves — the version table /health and probe_serving
            # print, and the rollout_replicas{version} gauge source.
            "weights_version": self.weights_version or None,
            "versions": versions,
            "canary": ({"replica": self._canary_idx,
                        "share": self._canary_share}
                       if self._canary_idx is not None else None),
            "active": counts[REPLICA_ACTIVE],
            "draining": counts[REPLICA_DRAINING],
            "ejected": counts[REPLICA_EJECTED],
            "migrations": self._migrations,
            "migrated_tokens": self._migrated_tokens,
            "hedges": self._hedges,
            "hedge_wins": self._hedge_wins,
            "drains": self._drains,
            "ejects": self._ejects,
            "rejoins": self._rejoins,
            "last_reset": (time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(last_wall)) + "Z"
                if last_wall else None),
            "last_reset_cause": last_cause,
            "replicas": reps,
        }

    #: stats() keys summed across replicas (everything else is either a
    #: config echo taken from the first reporting replica or fleet-local).
    _SUM_KEYS = ("batch_occupancy", "queue_depth", "kv_pages_used",
                 "kv_pages_total", "queue_rejections", "wasted_decode_steps",
                 "chunks_dispatched", "chunks_consumed", "chunks_pruned",
                 "pipe_inflight", "device_active_slots",
                 "tokens_per_sec_window", "fetches")

    def stats(self) -> dict:
        """Fleet-wide aggregation of the replica schedulers' stats, plus
        the ``fleet`` section the /metrics scrape mirrors into the
        per-replica gauges and migration/hedge counters."""
        agg: dict = {k: 0 for k in self._SUM_KEYS}
        fetch_samples: List[float] = []
        containment: dict = {"resets": {}, "quarantined": {},
                             "health_trips": 0, "replayed_tokens": 0,
                             "replayed_requests": 0, "parked": 0}
        per_replica = []
        replica_stats = []
        for rep in self.replicas:
            fn = getattr(rep.engine, "stats", None)
            s = {}
            if callable(fn):
                try:
                    s = fn() or {}
                except Exception:  # pragma: no cover - stopped replica
                    s = {}
            replica_stats.append(s)
            for k in self._SUM_KEYS:
                v = s.get(k)
                if isinstance(v, (int, float)):
                    agg[k] += v
            for k in ("pipe_depth", "max_queue_depth"):
                if k in s:
                    agg[k] = max(agg.get(k, 0), s[k])
            if "device_termination" in s:
                agg["device_termination"] = s["device_termination"]
            fetch_samples.extend(s.get("chunk_fetch_secs", ()))
            c = s.get("containment") or {}
            for cause, n in c.get("resets", {}).items():
                containment["resets"][cause] = (
                    containment["resets"].get(cause, 0) + n)
            for reason, n in c.get("quarantined", {}).items():
                containment["quarantined"][reason] = (
                    containment["quarantined"].get(reason, 0) + n)
            for k in ("health_trips", "replayed_tokens",
                      "replayed_requests", "parked"):
                containment[k] += c.get(k, 0)
            per_replica.append({
                "replica": rep.idx,
                "state": rep.state,
                "breaker": rep.breaker.state,
                "inflight": rep.inflight,
                "occupancy": s.get("batch_occupancy", rep.occupancy()),
                "queue_depth": s.get("queue_depth", 0),
                "migrations_out": rep.migrations_out,
                "weights_version": rep.weights_version() or None,
            })
        agg["chunk_fetch_secs"] = fetch_samples
        agg["containment"] = containment
        # QoS aggregation: depths/occupancy/counters sum; brownout is
        # the worst replica's level (the fleet is as browned-out as its
        # most-pressured member).
        qos: dict = {"lane_depth": {}, "lane_occupancy": {},
                     "expired": 0, "displaced": 0, "preemptions": 0,
                     "preempted_tokens": 0, "brownout_level": 0,
                     "tenants": 0}
        have_qos = False
        for s in replica_stats:
            q = s.get("qos")
            if not q:
                continue
            have_qos = True
            for key in ("lane_depth", "lane_occupancy"):
                for lane, n in (q.get(key) or {}).items():
                    qos[key][lane] = qos[key].get(lane, 0) + n
            for key in ("expired", "displaced", "preemptions",
                        "preempted_tokens", "tenants"):
                qos[key] += q.get(key, 0)
            qos["brownout_level"] = max(qos["brownout_level"],
                                        q.get("brownout_level", 0))
        if have_qos:
            agg["qos"] = qos
        # Telemetry plane (ISSUE 8): lane-table ledgers merge (replicas
        # + the relay's hedge-loser ledger); SLO burn windows merge by
        # summed counts.
        led = [s["ledger"] for s in replica_stats if s.get("ledger")]
        if led:
            agg["ledger"] = obs_ledger.merge_snapshots(
                led + [self.ledger.snapshot()])
        slo = [s["slo"] for s in replica_stats if s.get("slo")]
        if slo:
            agg["slo"] = obs_slo.merge_snapshots(slo)
        # KV pool (ISSUE 10): block-state counts + sharing/radix
        # counters sum across replicas (each owns its own pool).
        if any(s.get("kv_pool") for s in replica_stats):
            agg["kv_pool"] = self.kv_pool_health() or None
        # Grammar (ISSUE 11): forced/masked/dead-end totals sum; the
        # compiled identity passes through (replicas share one config).
        if any(s.get("grammar") for s in replica_stats):
            agg["grammar"] = self.grammar_health() or None
        # Speculative decoding (ISSUE 12): drafted/accepted totals sum,
        # acceptance re-derived from the sums.
        if any(s.get("spec") for s in replica_stats):
            agg["spec"] = self.spec_health() or None
        # Sharding (ISSUE 14): one config fleet-wide — pass-through
        # with the kv_pool_mesh_fallback flag OR-ed across replicas.
        if any(s.get("sharding") for s in replica_stats):
            agg["sharding"] = self.sharding_health() or None
        # Step-time sentinel (ISSUE 15): per-replica digests merged
        # with replica attribution on breaches.
        if any(s.get("steptime") for s in replica_stats):
            agg["steptime"] = self.steptime_health() or None
        fleet = self.fleet_health()
        fleet["replicas"] = per_replica
        agg["fleet"] = fleet
        return agg
