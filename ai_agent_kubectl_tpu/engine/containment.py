"""Blast-radius containment for the continuous batcher (the INNER ring).

PR 1 built the OUTER containment ring — bounded admission, circuit
breaker, degraded fallback — which keeps a broken *engine* from taking
down the *service*. This module is the inner ring: it keeps a broken
*request* (or one flaky device step) from taking down the *engine*.
Continuous batching colocates dozens of unrelated requests per decode
step, so without it one poisoned request fails every cohabitant — and a
long-decode victim loses hundreds of already-generated tokens.

Three mechanisms, shared by ``BatchedJaxEngine`` and
``FakeChunkedEngine`` (both schedulers call into one
``EngineSupervisor``):

1. **Detection** — the packed chunk contract (protocol.py v2) carries a
   per-slot health word written device-side (NaN/Inf logits,
   out-of-range sampled token ids), and the scheduler's step ``except``
   marks the step *poisoned* instead of failing every slot.
2. **Quarantine** — a culprit-isolation pass: a health bit names its
   slot directly; a step-wide fault bisects (replay half the survivors,
   park the rest) until the culprit runs alone. A confirmed culprit is
   failed with a terminal 410-style ``RequestQuarantined`` once its
   per-request ``QUARANTINE_RETRY_BUDGET`` is spent — never an infinite
   replay loop.
3. **Reset-and-replay** — decode state (KV cache, slot vectors, the
   speculative chunk pipeline) is torn down and re-initialized, then
   every surviving request is re-spliced from prompt + generated-so-far
   prefix and replayed under its recorded per-request sampling seed
   (engine/sampling.py ``slot_keys``), so recovered transcripts are
   bit-identical to a fault-free run. Resets are rate-limited
   (``ENGINE_RESET_MAX_PER_MIN``); past the limit the engine falls back
   to the PR 1 fail-fast path whose errors open the breaker, and every
   reset is also reported to the breaker through ``on_reset`` so a
   flapping engine degrades gracefully instead of flapping forever.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

#: reset cause labels (the ``engine_resets_total{cause}`` label set —
#: fixed here so metric cardinality is bounded by construction).
CAUSE_SLOT_HEALTH = "slot_health"          # device health word tripped
CAUSE_SCHEDULER_ERROR = "scheduler_error"  # exception in a scheduler step
CAUSE_SCHEDULER_DEATH = "scheduler_death"  # scheduler thread/task died

#: quarantine reason labels (``quarantined_requests_total{reason}``).
REASON_HEALTH = "slot_health"      # repeatedly tripped the health word
REASON_ISOLATED = "step_poison"    # bisect isolated it as the step poisoner

#: Early exoneration for bisection probation: once the probe group has
#: consumed this many chunks clean, the parked half is unparked and
#: admissions resume WITHOUT waiting for the probe to drain to empty —
#: otherwise one transient step-wide fault under long generations would
#: stall every admission for the probe's whole remaining decode (minutes
#: at max_tokens=512), converting a recovered fault into a service-wide
#: timeout storm. The cost: an intermittent fault that next trips after
#: re-mixing restarts bisection from the full survivor set — extra reset
#: rounds (still budgeted by ENGINE_RESET_MAX_PER_MIN), never a wrong
#: quarantine (terminal blame always requires solo implication or a
#: health-named slot, under the per-request retry budget).
PROBATION_CLEAN_CHUNKS = 2


class EngineSupervisor:
    """Reset/quarantine bookkeeping + policy for one engine instance.

    The engine's scheduler calls in from its own thread (or task); all
    mutation is behind one lock so ``stats()`` reads from the metrics
    scrape path are coherent. The supervisor owns POLICY (budgets, rate
    limit, counters); the MECHANISM of tearing down device state and
    re-splicing requests stays in the engine, which knows its buffers.
    """

    def __init__(self, *, retry_budget: int = 1,
                 max_resets_per_min: int = 6,
                 timer: Callable[[], float] = time.monotonic):
        #: how many times one request may be solo-implicated (health bit,
        #: or isolated by bisect) and still be replayed. Exceeding it is
        #: terminal: RequestQuarantined. 0 = quarantine on first trip.
        self.retry_budget = max(0, retry_budget)
        #: engine resets allowed per rolling minute; 0 = unlimited.
        #: Past the limit the engine must NOT reset again (it falls back
        #: to failing the affected requests — the PR 1 outer ring).
        self.max_resets_per_min = max(0, max_resets_per_min)
        self._timer = timer
        self._lock = threading.Lock()
        self._reset_times: deque = deque()
        self.resets: Dict[str, int] = {}
        self.quarantined: Dict[str, int] = {}
        self.health_trips = 0
        self.replayed_tokens = 0
        self.replayed_requests = 0
        self.last_reset_wall: Optional[float] = None   # time.time()
        self.last_reset_cause: Optional[str] = None
        #: fleet escalation (engine/fleet.py): how often allow_reset()
        #: said NO — the signal that this engine stopped recovering and
        #: degraded to fail-fast. The fleet monitor reads the wall stamp
        #: to label the ensuing ejection "reset_budget_exhausted"
        #: (replace/rejoin the replica) instead of a generic not-ready.
        self.budget_denials = 0
        self.last_denial_wall: Optional[float] = None
        #: optional listener invoked (cause) AFTER each recorded reset —
        #: the service layer wires this to the PR 1 circuit breaker so a
        #: reset storm opens it even while individual requests recover.
        self.on_reset: Optional[Callable[[str], None]] = None

    # ------------------------------------------------------------- policy

    def allow_reset(self) -> bool:
        """May the engine reset NOW? False once the rolling-minute budget
        is spent — the caller must degrade to fail-fast instead (whose
        errors feed the breaker), not reset in a tight loop."""
        if self.max_resets_per_min <= 0:
            return True
        with self._lock:
            self._prune_locked()
            allowed = len(self._reset_times) < self.max_resets_per_min
            if not allowed:
                self.budget_denials += 1
                self.last_denial_wall = time.time()
            return allowed

    def _prune_locked(self) -> None:
        horizon = self._timer() - 60.0
        while self._reset_times and self._reset_times[0] <= horizon:
            self._reset_times.popleft()

    def implicate(self, req) -> bool:
        """One request was solo-implicated (its health bit tripped, or
        bisect isolated it). Bumps ``req.suspect_count`` — the field
        lives on the request object so it survives resets, parking, and
        re-splices. Returns True when the retry budget is now exhausted
        → the caller quarantines the request terminally; False → the
        caller replays it (one more chance — a transient device fault
        must not kill an innocent request)."""
        req.suspect_count += 1
        return req.suspect_count > self.retry_budget

    @staticmethod
    def split(suspects: List) -> Tuple[List, List]:
        """Bisection step for a step-wide fault with an unknown culprit:
        (probe, parked). The probe half replays now; the parked half is
        held out until the probe either drains clean (innocent — unpark)
        or poisons another step (recurse into the probe's survivors)."""
        mid = (len(suspects) + 1) // 2
        return suspects[:mid], suspects[mid:]

    # ---------------------------------------------------------- recording

    def note_reset(self, cause: str) -> None:
        with self._lock:
            self._reset_times.append(self._timer())
            self.resets[cause] = self.resets.get(cause, 0) + 1
            self.last_reset_wall = time.time()
            self.last_reset_cause = cause
        listener = self.on_reset
        if listener is not None:
            try:
                listener(cause)
            except Exception:  # pragma: no cover - listener is best-effort
                pass

    def note_quarantine(self, reason: str) -> None:
        with self._lock:
            self.quarantined[reason] = self.quarantined.get(reason, 0) + 1

    def note_health_trips(self, n: int = 1) -> None:
        with self._lock:
            self.health_trips += n

    def note_replay(self, tokens: int) -> None:
        with self._lock:
            self.replayed_requests += 1
            self.replayed_tokens += max(0, tokens)

    # ------------------------------------------------------ observability

    def stats(self) -> dict:
        """Cumulative totals for the metrics delta-mirror
        (server/metrics.py ``observe_containment``) and /health."""
        with self._lock:
            return {
                "resets": dict(self.resets),
                "quarantined": dict(self.quarantined),
                "health_trips": self.health_trips,
                "replayed_tokens": self.replayed_tokens,
                "replayed_requests": self.replayed_requests,
                "retry_budget": self.retry_budget,
                "max_resets_per_min": self.max_resets_per_min,
                "last_reset_wall": self.last_reset_wall,
                "last_reset_cause": self.last_reset_cause,
                "budget_denials": self.budget_denials,
            }
