"""Zero-downtime weight rollout: versioned checkpoints, canary replicas,
an SLO-burn promotion gate, and automatic rollback.

ROADMAP item 5's lifecycle half: before this module the only way to
change the weights a fleet serves was to restart the process, and
nothing stood between a bad checkpoint and the whole fleet eating it at
once. Every primitive already existed — PR 6's ``drain()`` migrates
in-flight work off a replica, PR 8's goodput ledger and multi-window
SLO burn rates are exactly a promotion gate — so the controller here is
deliberately *composition*, not a new serving mechanism:

    drain → swap → warmup → rejoin → observe → promote-or-rollback

1. **Versioned checkpoints** — a weights version is a content
   fingerprint of the checkpoint path (``checkpoint_version``): path +
   file manifest (names, sizes, mtimes), 12 hex chars. Every engine
   stamps the version it serves (``engine.weights_version``) into
   ``/health``, per-replica, and the fleet echoes it as the
   ``X-Model-Version`` response header.
2. **Canary phase** — exactly ONE replica is drained, swapped to the
   new weights (the swap reuses the already-compiled program sets:
   same shapes/buckets ⇒ zero re-trace, only the device buffers
   change — a swapped replica's first request must not pay a
   multi-second compile), warmed, and rejoined. The fleet router then
   steers a bounded fraction of FRESH traffic (``ROLLOUT_CANARY_SHARE``,
   clamped so the canary can never starve the interactive lane) at it.
3. **Promotion gate** — over ``ROLLOUT_OBSERVE_SECS`` the canary is
   compared against the stable cohort on SLO burn (the fast window's
   ``fast_burn``), goodput ratio (delivered / total ledger steps),
   quarantine + grammar-dead-end counter deltas, and its breaker. A
   healthy canary promotes: the remaining replicas swap one at a time
   (each a drain → swap → rejoin cycle; established streams finish in
   place on the draining replica — see the version-pinning rule below).
4. **Automatic rollback** — on any gate breach, operator abort, or
   mid-swap fault the fleet is rolled back: every replica already on
   the new version drains, restores the prior checkpoint, and rejoins;
   ``rollout_rollbacks_total{cause}`` names why. A replica that died
   MID-swap (``swap:fail``) stays ejected with cause ``swap_failed`` —
   its buffers are gone; resurrecting it with unknown weights would be
   worse than serving degraded on N-1 replicas.

Correctness spine (enforced in engine/fleet.py, asserted in
tests/test_rollout.py): a cross-version replay cannot be byte-identical
— the transcript is a function of the weights — so migration, hedging,
and replay failover are pinned to same-version replicas only. An
ESTABLISHED stream (any generated/delivered prefix) is unroutable to a
version-mismatched candidate; a fresh request (nothing generated)
routes freely and replays from scratch on the new version as a fresh
request. Never a cross-version splice.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: rollout lifecycle states (closed set — the ``rollout_state`` gauge
#: encodes them by index, so order is part of the metric contract).
STATE_IDLE = "idle"
STATE_DRAINING = "draining"
STATE_SWAPPING = "swapping"
STATE_WARMING = "warming"
STATE_OBSERVING = "observing"
STATE_PROMOTING = "promoting"
STATE_ROLLING_BACK = "rolling_back"
STATE_ROLLED_BACK = "rolled_back"
STATE_COMPLETE = "complete"
STATE_FAILED = "failed"
ROLLOUT_STATES = (STATE_IDLE, STATE_DRAINING, STATE_SWAPPING,
                  STATE_WARMING, STATE_OBSERVING, STATE_PROMOTING,
                  STATE_ROLLING_BACK, STATE_ROLLED_BACK, STATE_COMPLETE,
                  STATE_FAILED)

#: rollback cause labels (``rollout_rollbacks_total{cause}`` — closed
#: here so metric cardinality is bounded by construction).
CAUSE_BURN_GATE = "burn_gate"            # canary SLO burn breached
CAUSE_GOODPUT_GATE = "goodput_gate"      # canary goodput ratio collapsed
CAUSE_COUNTER_GATE = "counter_gate"      # quarantines / grammar dead ends
CAUSE_CANARY_DOWN = "canary_down"        # canary ejected / breaker open
CAUSE_SWAP_FAILED = "swap_failed"        # replica died mid-swap
CAUSE_CHECKPOINT_CORRUPT = "checkpoint_corrupt"  # rejected at load
CAUSE_WARMUP_FAILED = "warmup_failed"    # rejoin/start failed post-swap
CAUSE_STEPTIME_GATE = "steptime_gate"    # canary decode p95 regressed
CAUSE_ABORTED = "aborted"                # operator POST /admin/rollout/abort
ROLLBACK_CAUSES = (CAUSE_BURN_GATE, CAUSE_GOODPUT_GATE,
                   CAUSE_COUNTER_GATE, CAUSE_CANARY_DOWN,
                   CAUSE_SWAP_FAILED, CAUSE_CHECKPOINT_CORRUPT,
                   CAUSE_WARMUP_FAILED, CAUSE_STEPTIME_GATE,
                   CAUSE_ABORTED)


class RolloutError(RuntimeError):
    """Rollout lifecycle misuse (already in progress, nothing to abort,
    same-version no-op) — maps to HTTP 409 at the admin endpoint."""


class CheckpointCorrupt(RolloutError):
    """The new checkpoint failed integrity validation at LOAD time
    (unreadable, wrong tree structure/shapes for the serving model, or
    the ``checkpoint:corrupt`` drill). The swap is atomic: the engine
    still holds — and keeps serving — the prior weights."""


class SwapFailed(RolloutError):
    """The replica died MID-swap (``swap:fail`` drill, or a real device
    fault between releasing the old buffers and arming the new ones).
    Unlike :class:`CheckpointCorrupt` the prior weights are NOT intact:
    the replica stays ejected with cause ``swap_failed`` until an
    operator re-swaps or replaces it."""


def checkpoint_version(path: Optional[str]) -> str:
    """Content fingerprint of a checkpoint path → 12-hex version id.

    The hash covers the path string plus, when the path exists, a
    manifest of its files (relative name, size, mtime) — cheap even for
    a 17 GB checkpoint (no data read) yet it changes whenever any shard
    is replaced in place. A path that does not exist still versions
    deterministically (dev/toy mode serves random-init weights keyed on
    the path, so the same "checkpoint" name always means the same
    weights)."""
    h = hashlib.sha256(str(path or "").encode("utf-8", "surrogatepass"))
    try:
        import os

        p = str(path or "")
        if p and os.path.isdir(p):
            for root, _dirs, files in sorted(os.walk(p)):
                for name in sorted(files):
                    full = os.path.join(root, name)
                    st = os.stat(full)
                    rel = os.path.relpath(full, p)
                    h.update(f"{rel}:{st.st_size}:{st.st_mtime_ns}"
                             .encode())
        elif p and os.path.isfile(p):
            st = os.stat(p)
            h.update(f"{st.st_size}:{st.st_mtime_ns}".encode())
    except OSError:  # pragma: no cover - racing filesystem change
        pass
    return h.hexdigest()[:12]


def fast_burn_from_snapshot(snap: Optional[dict]) -> Optional[float]:
    """Worst fast-window burn rate across every (slo, lane) of an
    ``slo_health()`` snapshot — the promotion gate's burn signal. None
    when the snapshot has no samples (no data must not read as healthy
    OR as breaching, same rule as ``SloEngine.fast_burn``)."""
    if not snap:
        return None
    windows = snap.get("windows") or []
    if not windows:
        return None
    fast = windows[0]
    best: Optional[float] = None
    for body in (snap.get("slos") or {}).values():
        for row in (body.get("lanes") or {}).values():
            win = (row.get("windows") or {}).get(fast)
            if win and win.get("total"):
                burn = float(win.get("burn_rate", 0.0))
                best = burn if best is None else max(best, burn)
    return best


def _merge_slo(snaps: List[dict]) -> dict:
    from ..obs import slo as obs_slo

    return obs_slo.merge_snapshots([s for s in snaps if s])


class RolloutController:
    """Drives one weight rollout at a time over an :class:`EngineFleet`
    (or, degenerately, a single swap-capable engine).

    The controller owns POLICY (which replica is the canary, when the
    gate breaches, what rolls back); the MECHANISM stays where it
    already lives — ``fleet.drain/rejoin`` for lifecycle,
    ``engine.swap_weights`` for the buffer swap, the router's
    version-pinning for stream correctness."""

    #: gate poll cadence while observing (fraction of the observe
    #: window, clamped to a sane range so tests with sub-second windows
    #: still poll several times).
    GATE_POLL_MIN_SECS = 0.02
    GATE_POLL_MAX_SECS = 1.0
    #: minimum canary ledger steps before the goodput gate may judge —
    #: a 3-step sample must not roll back a healthy checkpoint.
    MIN_GATE_STEPS = 20
    #: canary goodput must stay above this fraction of stable's.
    GOODPUT_GATE_FACTOR = 0.5

    def __init__(self, engine, *,
                 canary_share: float = 0.1,
                 observe_secs: float = 60.0,
                 burn_gate: float = 2.0,
                 steptime_gate: float = 0.0,
                 drain_secs: float = 10.0):
        # Clamp the canary share away from interactive-lane starvation:
        # at most half the fresh traffic may be steered at one replica,
        # and a zero share still routes *pinned* work correctly (the
        # canary then only sees traffic the accumulator never sends —
        # i.e. none — which makes the observe phase meaningless, so the
        # floor is a nominal trickle).
        self.canary_share = min(max(float(canary_share), 0.01), 0.5)
        self.observe_secs = max(0.0, float(observe_secs))
        self.burn_gate = max(1.0, float(burn_gate))
        # Optional canary-vs-stable STEP-TIME verdict (ISSUE 15): the
        # canary rolls back when its decode/spec_verify p95 reaches
        # this multiple of the stable cohort's on the same (phase,
        # bucket) key (obs/steptime.py canary_vs_stable). 0 = off —
        # the burn gate already catches latency the client can feel;
        # this one catches "the canary is 30% slower per step" before
        # any SLO breaches.
        self.steptime_gate = (0.0 if steptime_gate <= 0
                              else max(1.0, float(steptime_gate)))
        self.drain_secs = max(0.0, float(drain_secs))
        self.engine = engine
        self.state = STATE_IDLE
        self.target_version: Optional[str] = None
        self.target_checkpoint: Optional[str] = None
        self.prior_version: Optional[str] = None
        self.prior_checkpoint: Optional[str] = None
        self.canary_idx: Optional[int] = None
        self.started_wall: Optional[float] = None
        self.observe_deadline: Optional[float] = None   # monotonic
        self.last_gate: Optional[dict] = None
        self.last_rollback_cause: Optional[str] = None
        self.last_error: Optional[str] = None
        #: cumulative rollbacks by cause — the /metrics delta-mirror
        #: source (totals never go backwards).
        self.rollbacks: Dict[str, int] = {}
        self.rollouts_started = 0
        self.rollouts_completed = 0
        #: rollout timeline (drain/swap/rejoin/promote per replica) —
        #: the controller runs outside any request, so it keeps its own
        #: link log in lieu of a request trace; status() exposes it.
        self.events: deque = deque(maxlen=256)
        self.history: deque = deque(maxlen=16)
        self._task: Optional[asyncio.Task] = None
        self._abort = asyncio.Event()

    # ------------------------------------------------------------- seams

    @property
    def _fleet(self):
        """The EngineFleet when the engine is one (duck-typed on
        ``replicas``); None for a bare swap-capable engine."""
        return self.engine if hasattr(self.engine, "replicas") else None

    def _replica_engines(self) -> List[Tuple[int, object]]:
        fleet = self._fleet
        if fleet is None:
            return [(0, self.engine)]
        return [(rep.idx, rep.engine) for rep in fleet.replicas]

    def _version_of(self, engine) -> str:
        return str(getattr(engine, "weights_version", "") or "")

    def _link(self, link_type: str, **meta) -> None:
        """One timeline event (the rollout's own stitched trace)."""
        entry = {"t": round(time.time(), 3), "type": link_type, **meta}
        self.events.append(entry)
        logger.info("rollout: %s %s", link_type,
                    {k: v for k, v in meta.items()})

    # ----------------------------------------------------------- surface

    @property
    def active(self) -> bool:
        return self._task is not None and not self._task.done()

    def replica_versions(self) -> Dict[str, str]:
        return {str(idx): self._version_of(eng)
                for idx, eng in self._replica_engines()}

    def health(self) -> dict:
        """Cheap view for /health and the metrics mirror (never calls
        engine stats())."""
        return {
            "state": self.state,
            "active": self.active,
            "target_version": self.target_version,
            "stable_version": self._stable_version(),
            "canary_replica": self.canary_idx,
            "canary_share": self.canary_share,
            "replica_versions": self.replica_versions(),
            "rollbacks_total": dict(self.rollbacks),
            "rollouts_started": self.rollouts_started,
            "rollouts_completed": self.rollouts_completed,
            "last_rollback_cause": self.last_rollback_cause,
        }

    def status(self) -> dict:
        """Full operator view for GET /admin/rollout."""
        body = self.health()
        body.update({
            "target_checkpoint": self.target_checkpoint,
            "prior_version": self.prior_version,
            "prior_checkpoint": self.prior_checkpoint,
            "observe_secs": self.observe_secs,
            "observe_remaining": (
                round(max(0.0, self.observe_deadline - time.monotonic()), 3)
                if (self.observe_deadline is not None
                    and self.state == STATE_OBSERVING) else None),
            "burn_gate": self.burn_gate,
            "last_gate": self.last_gate,
            "last_error": self.last_error,
            "events": list(self.events),
            "history": list(self.history),
        })
        return body

    def _stable_version(self) -> Optional[str]:
        fleet = self._fleet
        if fleet is not None:
            v = getattr(fleet, "weights_version", None)
            return v or None
        return self._version_of(self.engine) or None

    # --------------------------------------------------------- lifecycle

    async def start_rollout(self, checkpoint: str,
                            version: Optional[str] = None) -> dict:
        """Begin a rollout to ``checkpoint``. Returns the initial
        status; the state machine runs as a background task."""
        if self.active:
            raise RolloutError(
                f"a rollout to {self.target_version} is already in "
                f"progress ({self.state}); abort it first")
        if not checkpoint or not str(checkpoint).strip():
            raise RolloutError("rollout needs a checkpoint path")
        # Every replica must actually be swappable BEFORE anything
        # drains: accepting the rollout and then discovering a
        # swap-less engine mid-cycle would eject a healthy replica
        # (the mid-swap-death arm) over an operator typo.
        unswappable = [idx for idx, eng in self._replica_engines()
                       if not callable(getattr(eng, "swap_weights",
                                               None))]
        if unswappable:
            raise RolloutError(
                f"replica(s) {unswappable} run an engine without "
                f"swap_weights support; rollout refused")
        checkpoint = str(checkpoint).strip()
        version = version or checkpoint_version(checkpoint)
        stable = self._stable_version()
        if stable == version:
            raise RolloutError(
                f"fleet already serves weights version {version}")
        self.rollouts_started += 1
        self.state = STATE_DRAINING
        self.target_version = version
        self.target_checkpoint = checkpoint
        self.prior_version = stable
        # The prior checkpoint path is whatever the (first) stable
        # replica loaded — swap_weights keeps engine.checkpoint_path
        # current, and _load seeds it from MODEL_PATH.
        self.prior_checkpoint = next(
            (getattr(eng, "checkpoint_path", None)
             for _, eng in self._replica_engines()
             if self._version_of(eng) == (stable or "")), None)
        self.last_rollback_cause = None
        self.last_error = None
        self.last_gate = None
        self.started_wall = time.time()
        self._abort.clear()
        self._link("rollout_started", version=version,
                   checkpoint=checkpoint, prior=stable)
        self._task = asyncio.create_task(self._run())
        return self.status()

    async def abort(self) -> dict:
        """Operator abort: the running rollout rolls back (cause
        ``aborted``); a finished one is a 409."""
        if not self.active:
            raise RolloutError("no rollout in progress")
        self._abort.set()
        try:
            await asyncio.wait_for(asyncio.shield(self._task), 30.0)
        except asyncio.TimeoutError:  # pragma: no cover - hung engine stop
            pass
        return self.status()

    # ------------------------------------------------------ the machine

    async def _run(self) -> None:
        try:
            await self._run_inner()
        except asyncio.CancelledError:  # pragma: no cover - teardown
            raise
        except Exception as e:  # pragma: no cover - defensive backstop
            logger.exception("rollout: unexpected failure")
            self.last_error = f"{type(e).__name__}: {e}"
            self.state = STATE_FAILED
            self._finish_history()

    async def _run_inner(self) -> None:
        fleet = self._fleet
        replicas = self._replica_engines()
        version = self.target_version
        path = self.target_checkpoint

        # Canary pick: least-loaded active replica (ties by index, so an
        # idle fleet deterministically canaries replica 0).
        if fleet is not None:
            active = [rep for rep in fleet.replicas
                      if rep.state == "active"]
            if not active:
                self.last_error = "no active replica to canary"
                self.state = STATE_FAILED
                self._finish_history()
                return
            canary = min(active, key=lambda r: (r.inflight, r.idx))
            self.canary_idx = canary.idx
        else:
            self.canary_idx = 0

        # ---- canary: drain → swap → warmup → rejoin --------------------
        ok = await self._swap_one(self.canary_idx, path, version,
                                  first=True)
        if not ok:
            return   # _swap_one already rolled back / recorded the cause

        single = len(replicas) <= 1
        if single:
            # Degenerate fleet: there is no stable cohort to gate the
            # canary against — the in-place swap IS the rollout.
            self._link("promote", replica=self.canary_idx,
                       version=version, note="single replica; canary "
                       "gate skipped (no stable cohort)")
            self._complete()
            return

        # ---- observe: canary serves a bounded share ---------------------
        self.state = STATE_OBSERVING
        baseline = self._gate_baseline()
        if fleet is not None:
            fleet.set_canary(self.canary_idx, self.canary_share)
        self.observe_deadline = time.monotonic() + self.observe_secs
        poll = min(max(self.observe_secs / 20.0, self.GATE_POLL_MIN_SECS),
                   self.GATE_POLL_MAX_SECS)
        self._link("observe", replica=self.canary_idx, version=version,
                   secs=self.observe_secs, share=self.canary_share)
        try:
            while time.monotonic() < self.observe_deadline:
                if self._abort.is_set():
                    await self._rollback(CAUSE_ABORTED)
                    return
                gate = self._evaluate_gate(baseline)
                self.last_gate = gate
                if gate["breach"]:
                    await self._rollback(gate["cause"])
                    return
                await asyncio.sleep(poll)
            # Final evaluation at the deadline: the gate must PASS to
            # promote, not merely never have been polled breaching.
            gate = self._evaluate_gate(baseline)
            self.last_gate = gate
            if gate["breach"]:
                await self._rollback(gate["cause"])
                return
        finally:
            if fleet is not None:
                fleet.clear_canary()
            self.observe_deadline = None

        # ---- promote: roll the stable cohort one replica at a time ------
        self.state = STATE_PROMOTING
        for idx, eng in replicas:
            if idx == self.canary_idx:
                continue
            if self._abort.is_set():
                await self._rollback(CAUSE_ABORTED)
                return
            if self._version_of(eng) == version:
                continue
            ok = await self._swap_one(idx, path, version, first=False)
            if not ok:
                return
            self._link("promote", replica=idx, version=version)
        self._complete()

    def _complete(self) -> None:
        self.state = STATE_COMPLETE
        self.rollouts_completed += 1
        self._link("rollout_complete", version=self.target_version)
        self._finish_history()

    def _finish_history(self) -> None:
        self.history.append({
            "version": self.target_version,
            "prior": self.prior_version,
            "state": self.state,
            "cause": self.last_rollback_cause,
            "started": self.started_wall,
            "finished": time.time(),
        })

    # -------------------------------------------------- swap + rollback

    async def _swap_one(self, idx: int, path: str, version: str, *,
                        first: bool, rolling_back: bool = False) -> bool:
        """One replica's drain → swap → warmup → rejoin. Returns False
        after handling the failure (rollback recorded) — except while
        already rolling back, where failures just log and continue."""
        fleet = self._fleet
        eng = dict(self._replica_engines())[idx]
        hint = max(2.0, self.drain_secs / 2.0)

        def phase(state: str) -> None:
            # Only the CANARY's cycle narrates the fine-grained states;
            # promote/rollback cycles keep the coarse machine state.
            if first and not rolling_back:
                self.state = state

        phase(STATE_DRAINING)
        self._link("drain", replica=idx,
                   to_version=version)
        try:
            try:
                if fleet is not None:
                    fleet.swap_hint = hint
                    await fleet.drain(idx, drain_secs=self.drain_secs)
                else:
                    setattr(self.engine, "swap_hint", hint)
                    await eng.stop(drain_secs=self.drain_secs)
            except Exception as e:
                # A drain that raises leaves the engine half-stopped:
                # treat it like a mid-swap death (replica out of
                # rotation, attributably; the rollout rolls back) — NOT
                # the generic backstop, which would strand the replica
                # in `draining` with no rollback at all.
                self.last_error = f"{type(e).__name__}: {e}"
                self._link("drain_failed", replica=idx, error=str(e))
                if fleet is not None:
                    rep = fleet.replicas[idx]
                    rep.state = "ejected"
                    rep.eject_cause = "drain_failed"
                    rep.last_error = self.last_error
                if not rolling_back:
                    await self._rollback(CAUSE_SWAP_FAILED)
                return False
            phase(STATE_SWAPPING)
            self._link("swap", replica=idx, to_version=version)
            try:
                await asyncio.to_thread(eng.swap_weights, path,
                                        version=version)
            except CheckpointCorrupt as e:
                # Atomic swap: the prior weights are still armed — the
                # replica rejoins on them and the rollout rolls back.
                self.last_error = str(e)
                self._link("swap_rejected", replica=idx, error=str(e))
                try:
                    if fleet is not None:
                        await fleet.rejoin(idx)
                    else:
                        await eng.start()
                except Exception:  # pragma: no cover - engine-dependent
                    logger.exception(
                        "rollout: replica %d rejoin after corrupt "
                        "checkpoint failed", idx)
                if not rolling_back:
                    await self._rollback(CAUSE_CHECKPOINT_CORRUPT)
                return False
            except Exception as e:
                # Mid-swap death: the replica's buffers are in an
                # unknown state. It stays ejected, attributably.
                self.last_error = f"{type(e).__name__}: {e}"
                self._link("swap_failed", replica=idx, error=str(e))
                if fleet is not None:
                    rep = fleet.replicas[idx]
                    rep.state = "ejected"
                    rep.eject_cause = "swap_failed"
                    rep.last_error = self.last_error
                if not rolling_back:
                    await self._rollback(CAUSE_SWAP_FAILED)
                return False
            phase(STATE_WARMING)
            self._link("warmup", replica=idx, version=version)
            try:
                if fleet is not None:
                    await fleet.rejoin(idx)
                else:
                    await eng.start()
            except Exception as e:
                self.last_error = f"{type(e).__name__}: {e}"
                self._link("warmup_failed", replica=idx, error=str(e))
                if not rolling_back:
                    await self._rollback(CAUSE_WARMUP_FAILED)
                return False
            self._link("rejoin", replica=idx, version=version)
            return True
        finally:
            if fleet is not None:
                fleet.swap_hint = 0.0
            else:
                setattr(self.engine, "swap_hint", 0.0)

    async def _rollback(self, cause: str) -> None:
        """Restore every replica serving the target version to the
        prior checkpoint; replicas that died mid-swap stay ejected."""
        fleet = self._fleet
        self.state = STATE_ROLLING_BACK
        self.last_rollback_cause = cause
        self.rollbacks[cause] = self.rollbacks.get(cause, 0) + 1
        if fleet is not None:
            fleet.clear_canary()
        self._link("rollback", cause=cause,
                   from_version=self.target_version,
                   to_version=self.prior_version)
        prior_path = self.prior_checkpoint
        for idx, eng in self._replica_engines():
            if self._version_of(eng) != (self.target_version or ""):
                continue
            if not getattr(eng, "ready", False) and fleet is not None \
                    and fleet.replicas[idx].eject_cause == "swap_failed":
                continue   # dead mid-swap: stays ejected, documented
            if prior_path is None:
                # Nothing to restore onto (the prior engine ran without
                # a checkpoint path and no registry entry survived):
                # leave the replica serving the new weights but record
                # the failure loudly.
                self.last_error = ("rollback has no prior checkpoint "
                                  "path to restore")
                logger.error("rollout: %s", self.last_error)
                continue
            ok = await self._swap_one(idx, prior_path,
                                      self.prior_version
                                      or checkpoint_version(prior_path),
                                      first=False, rolling_back=True)
            if not ok:  # pragma: no cover - double fault
                logger.error("rollout: rollback of replica %d failed",
                             idx)
        self.state = STATE_ROLLED_BACK
        self._link("rollback_complete", cause=cause)
        self._finish_history()

    # -------------------------------------------------------- the gate

    def _gate_baseline(self) -> dict:
        """Counter snapshot at observe start: the gate judges DELTAS
        (a canary must not be blamed for quarantines that predate it)."""
        base: Dict[int, dict] = {}
        for idx, eng in self._replica_engines():
            base[idx] = self._replica_counters(eng)
        return base

    @staticmethod
    def _replica_counters(eng) -> dict:
        sup = getattr(eng, "supervisor", None)
        quar = sum(getattr(sup, "quarantined", {}).values()) if sup else 0
        dead = 0
        gh = getattr(eng, "grammar_health", None)
        if callable(gh):
            try:
                g = gh() or {}
            except Exception:   # pragma: no cover - stopped replica
                g = {}
            dead = sum((g.get("dead_ends_total") or {}).values())
        delivered = total = 0
        ls = getattr(eng, "ledger_snapshot", None)
        if callable(ls):
            try:
                snap = ls() or {}
            except Exception:   # pragma: no cover - stopped replica
                snap = {}
            classes = snap.get("classes") or {}
            delivered = int(classes.get("delivered", 0))
            total = int(snap.get("total_steps", 0))
        return {"quarantined": quar, "dead_ends": dead,
                "delivered": delivered, "total": total}

    def _evaluate_gate(self, baseline: dict) -> dict:
        """Canary-vs-stable verdict. Returns ``{"breach": bool,
        "cause": str | None, ...detail}`` and never raises — a gate that
        crashes must not wedge the state machine."""
        fleet = self._fleet
        detail: dict = {"breach": False, "cause": None}
        if fleet is None:
            return detail
        canary = fleet.replicas[self.canary_idx]
        stable = [rep for rep in fleet.replicas
                  if rep.idx != self.canary_idx
                  and rep.state == "active"]
        # 1. The canary fell over outright: ejected, not ready, or its
        # breaker opened — no statistics needed.
        if (canary.state != "active"
                or not getattr(canary.engine, "ready", False)
                or canary.breaker.state == "open"):
            detail.update(breach=True, cause=CAUSE_CANARY_DOWN,
                          canary_state=canary.state,
                          canary_breaker=canary.breaker.state)
            return detail
        # 2. Counter gate: new quarantines / grammar dead ends on the
        # canary in excess of the stable per-replica average.
        cnow = self._replica_counters(canary.engine)
        cbase = baseline.get(self.canary_idx,
                             {"quarantined": 0, "dead_ends": 0,
                              "delivered": 0, "total": 0})
        c_bad = ((cnow["quarantined"] - cbase["quarantined"])
                 + (cnow["dead_ends"] - cbase["dead_ends"]))
        s_bad = 0.0
        s_delivered = s_total = 0
        for rep in stable:
            snow = self._replica_counters(rep.engine)
            sbase = baseline.get(rep.idx, snow)
            s_bad += ((snow["quarantined"] - sbase["quarantined"])
                      + (snow["dead_ends"] - sbase["dead_ends"]))
            s_delivered += snow["delivered"] - sbase["delivered"]
            s_total += snow["total"] - sbase["total"]
        s_bad_avg = s_bad / max(1, len(stable))
        detail["canary_bad_counters"] = c_bad
        detail["stable_bad_counters_avg"] = round(s_bad_avg, 3)
        if c_bad > 0 and c_bad > s_bad_avg:
            detail.update(breach=True, cause=CAUSE_COUNTER_GATE)
            return detail
        # 3. Burn gate: the canary's fast-window burn vs the stable
        # cohort's (merged — rates recompute from summed counts). The
        # canary breaches when it burns >= ROLLOUT_BURN_GATE times the
        # worse of (sustainable rate 1.0, stable's own burn) — a fleet
        # already burning from ambient load must not auto-roll a canary
        # back for matching it.
        c_burn = self._safe_fast_burn(canary.engine)
        s_burn = fast_burn_from_snapshot(_merge_slo(
            [self._safe_slo(rep.engine) for rep in stable]))
        detail["canary_fast_burn"] = c_burn
        detail["stable_fast_burn"] = s_burn
        if c_burn is not None \
                and c_burn >= self.burn_gate * max(1.0, s_burn or 0.0):
            detail.update(breach=True, cause=CAUSE_BURN_GATE)
            return detail
        # 3b. Step-time gate (optional, ISSUE 15): canary-vs-stable
        # decode p95 on matching (phase, bucket) keys — a per-step
        # regression is visible long before enough requests breach an
        # SLO to move the burn rate. No comparable key ⇒ no verdict.
        if self.steptime_gate > 0:
            from ..obs import steptime as obs_steptime

            cmp = obs_steptime.canary_vs_stable(
                self._safe_steptime(canary.engine),
                [self._safe_steptime(rep.engine) for rep in stable])
            detail["steptime"] = cmp
            if cmp is not None and cmp["ratio"] >= self.steptime_gate:
                detail.update(breach=True, cause=CAUSE_STEPTIME_GATE)
                return detail
        # 4. Goodput gate: the canary's delivered fraction of ledger
        # steps since observe start vs stable's, once both cohorts have
        # a meaningful sample.
        c_delivered = cnow["delivered"] - cbase["delivered"]
        c_total = cnow["total"] - cbase["total"]
        detail["canary_goodput"] = (round(c_delivered / c_total, 4)
                                    if c_total else None)
        detail["stable_goodput"] = (round(s_delivered / s_total, 4)
                                    if s_total else None)
        if (c_total >= self.MIN_GATE_STEPS
                and s_total >= self.MIN_GATE_STEPS and s_delivered > 0):
            c_ratio = c_delivered / c_total
            s_ratio = s_delivered / s_total
            if c_ratio < self.GOODPUT_GATE_FACTOR * s_ratio:
                detail.update(breach=True, cause=CAUSE_GOODPUT_GATE)
                return detail
        return detail

    @staticmethod
    def _safe_slo(eng) -> dict:
        fn = getattr(eng, "slo_health", None)
        if not callable(fn):
            return {}
        try:
            return fn() or {}
        except Exception:   # pragma: no cover - stopped replica
            return {}

    @staticmethod
    def _safe_steptime(eng) -> dict:
        fn = getattr(eng, "steptime_health", None)
        if not callable(fn):
            return {}
        try:
            return fn() or {}
        except Exception:   # pragma: no cover - stopped replica
            return {}

    def _safe_fast_burn(self, eng) -> Optional[float]:
        return fast_burn_from_snapshot(self._safe_slo(eng))
