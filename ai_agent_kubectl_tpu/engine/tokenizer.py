"""Tokenizer layer.

The reference had no tokenizer (tokenization happened inside OpenAI's
service, SURVEY.md §2.2). Two implementations behind one interface:

- ``HFTokenizer``  — wraps a HuggingFace ``tokenizers`` fast tokenizer file
  (tokenizer.json) for real checkpoints (Gemma/Llama/Mixtral).
- ``ByteTokenizer`` — deterministic UTF-8 byte-level fallback for tests and
  the toy models: token = byte + 3, specials pad=0/bos=1/eos=2. No files,
  no network, fully reversible.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_ids: tuple
    pad_id: int

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes + 3 special tokens. vocab = 259."""

    SPECIALS = 3

    def __init__(self, pad_id: int = 0, bos_id: int = 1, eos_id: int = 2):
        self.pad_id = pad_id
        self.bos_id = bos_id
        self.eos_ids = (eos_id,)
        self.vocab_size = 256 + self.SPECIALS

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = [b + self.SPECIALS for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # Ignore specials and out-of-byte-range ids (toy vocabs may be
        # larger than 259; a random-init model can emit any id).
        data = bytes(
            i - self.SPECIALS
            for i in ids
            if self.SPECIALS <= i < 256 + self.SPECIALS
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """HuggingFace fast-tokenizer file (tokenizer.json)."""

    def __init__(self, path: str | Path, bos_id: int, eos_ids: tuple, pad_id: int):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(str(path))
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = bos_id
        self.eos_ids = tuple(eos_ids)
        self.pad_id = pad_id

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        specials = set(self.eos_ids) | {self.bos_id, self.pad_id}
        return self._tok.decode([i for i in ids if i not in specials])


class StreamDecoder:
    """Incremental detokenization with UTF-8 hold-back.

    A token can end mid-way through a multi-byte UTF-8 character, where
    ``decode()`` shows U+FFFD; trailing replacement chars are held back
    until the next token resolves them, so streamed pieces concatenate to
    exactly the final text with no transient mojibake. Genuinely invalid
    bytes (still U+FFFD after 3 more chars arrive) are released by
    ``push``; ``flush`` emits any held-back tail at end of stream.

    Decoding is incremental via a sliding prefix window (the scheme TGI and
    vLLM use): only ids from ``_prefix_idx`` on are re-decoded per push, and
    the newly-emitted piece is the *difference* between that window's decode
    with and without the unemitted tail. Because both decodes share the same
    window start, tokenizer behaviours that depend on sequence position
    (SentencePiece ``Strip(left)``, byte-fallback fusing) cancel out of the
    diff — chunk decodes are never naively concatenated. The window advances
    whenever its text is fully emitted, so per-push cost is independent of
    generation length.
    """

    #: Force-release threshold: a window this long that still ends in
    #: held-back U+FFFD is a garbage run, not a split character — emit it
    #: so per-push cost stays bounded even on adversarial byte streams.
    _WINDOW_CAP = 64

    def __init__(self, tokenizer: Tokenizer):
        self._tokenizer = tokenizer
        self.ids: List[int] = []
        self.text = ""
        self._prefix_idx = 0    # window start: left context for the decode
        self._read_idx = 0      # ids before this are fully emitted
        self._win_emitted = 0   # chars emitted beyond the prefix decode

    def _window(self) -> tuple:
        """(chars already emitted in window coordinates, window decode)."""
        prefix_text = self._tokenizer.decode(
            self.ids[self._prefix_idx:self._read_idx]
        )
        new_text = self._tokenizer.decode(self.ids[self._prefix_idx:])
        return len(prefix_text) + self._win_emitted, new_text

    def _advance(self) -> None:
        self._prefix_idx = self._read_idx
        self._read_idx = len(self.ids)
        self._win_emitted = 0

    def push(self, *new_ids: int) -> Optional[str]:
        """Add token ids; return the newly-stable text piece (or None)."""
        self.ids.extend(new_ids)
        base, new_text = self._window()
        stable = len(new_text)
        while (stable > base and new_text[stable - 1] == "�"
               and len(new_text) - stable < 3):
            stable -= 1
        if (stable < len(new_text)
                and len(self.ids) - self._prefix_idx > self._WINDOW_CAP):
            return self._force_release(base)
        piece = None
        emitted_to = base
        if stable > base:
            piece = new_text[base:stable]
            self.text += piece
            self._win_emitted += stable - base
            emitted_to = stable
        if emitted_to == len(new_text):
            self._advance()
        return piece

    def _force_release(self, base: int) -> Optional[str]:
        """Window overflow with a held-back tail: release the window, but
        advance only to the last id boundary whose decode is
        replacement-free — a split UTF-8 sequence still pending completion
        keeps its ids in the next window (advancing through it would make
        the next window's prefix decode disagree with the full decode and
        duplicate/drop characters). If no boundary in the unemitted tail is
        clean, the run is genuine garbage: release everything."""
        end = len(self.ids)
        j = None
        for cand in range(end, self._read_idx, -1):
            t = self._tokenizer.decode(self.ids[self._prefix_idx:cand])
            if not t.endswith("�"):
                j = cand
                break
        if j is None:
            j = end
            t = self._tokenizer.decode(self.ids[self._prefix_idx:])
        piece = t[base:] or None
        if piece:
            self.text += piece
        self._prefix_idx = j
        self._read_idx = j
        self._win_emitted = 0
        return piece

    def flush(self) -> Optional[str]:
        """Emit any held-back tail (end of stream)."""
        base, new_text = self._window()
        if len(new_text) > base:
            piece = new_text[base:]
            self.text += piece
            self._advance()
            return piece
        return None


def load_tokenizer(model_cfg, tokenizer_path: Optional[str]) -> Tokenizer:
    """Pick the tokenizer for a model config: HF file when provided/found,
    byte-level for toy models."""
    if tokenizer_path:
        p = Path(tokenizer_path)
        if p.is_dir():
            p = p / "tokenizer.json"
        return HFTokenizer(p, model_cfg.bos_id, model_cfg.eos_ids, model_cfg.pad_id)
    if model_cfg.name.startswith("toy"):
        return ByteTokenizer()
    raise FileNotFoundError(
        f"No TOKENIZER_PATH configured for model {model_cfg.name!r} "
        "(set TOKENIZER_PATH to a tokenizer.json or checkpoint dir)"
    )
