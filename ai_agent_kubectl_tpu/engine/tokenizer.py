"""Tokenizer layer.

The reference had no tokenizer (tokenization happened inside OpenAI's
service, SURVEY.md §2.2). Two implementations behind one interface:

- ``HFTokenizer``  — wraps a HuggingFace ``tokenizers`` fast tokenizer file
  (tokenizer.json) for real checkpoints (Gemma/Llama/Mixtral).
- ``ByteTokenizer`` — deterministic UTF-8 byte-level fallback for tests and
  the toy models: token = byte + 3, specials pad=0/bos=1/eos=2. No files,
  no network, fully reversible.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    vocab_size: int
    bos_id: int
    eos_ids: tuple
    pad_id: int

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]: ...

    def decode(self, ids: Sequence[int]) -> str: ...


class ByteTokenizer:
    """UTF-8 bytes + 3 special tokens. vocab = 259."""

    SPECIALS = 3

    def __init__(self, pad_id: int = 0, bos_id: int = 1, eos_id: int = 2):
        self.pad_id = pad_id
        self.bos_id = bos_id
        self.eos_ids = (eos_id,)
        self.vocab_size = 256 + self.SPECIALS

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = [b + self.SPECIALS for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        # Ignore specials and out-of-byte-range ids (toy vocabs may be
        # larger than 259; a random-init model can emit any id).
        data = bytes(
            i - self.SPECIALS
            for i in ids
            if self.SPECIALS <= i < 256 + self.SPECIALS
        )
        return data.decode("utf-8", errors="replace")


class HFTokenizer:
    """HuggingFace fast-tokenizer file (tokenizer.json)."""

    def __init__(self, path: str | Path, bos_id: int, eos_ids: tuple, pad_id: int):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(str(path))
        self.vocab_size = self._tok.get_vocab_size()
        self.bos_id = bos_id
        self.eos_ids = tuple(eos_ids)
        self.pad_id = pad_id

    def encode(self, text: str, *, add_bos: bool = True) -> List[int]:
        ids = self._tok.encode(text, add_special_tokens=False).ids
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids: Sequence[int]) -> str:
        specials = set(self.eos_ids) | {self.bos_id, self.pad_id}
        return self._tok.decode([i for i in ids if i not in specials])


class StreamDecoder:
    """Incremental detokenization with UTF-8 hold-back.

    A token can end mid-way through a multi-byte UTF-8 character, where
    ``decode()`` shows U+FFFD; trailing replacement chars are held back
    until the next token resolves them, so streamed pieces concatenate to
    exactly the final text with no transient mojibake. Genuinely invalid
    bytes (still U+FFFD after 3 more chars arrive) are released by
    ``push``; ``flush`` emits any held-back tail at end of stream.
    """

    def __init__(self, tokenizer: Tokenizer):
        self._tokenizer = tokenizer
        self.ids: List[int] = []
        self.text = ""
        self._emitted = 0

    def push(self, *new_ids: int) -> Optional[str]:
        """Add token ids; return the newly-stable text piece (or None)."""
        self.ids.extend(new_ids)
        self.text = self._tokenizer.decode(self.ids)
        stable = len(self.text)
        while (stable > self._emitted and self.text[stable - 1] == "�"
               and len(self.text) - stable < 3):
            stable -= 1
        if stable > self._emitted:
            piece = self.text[self._emitted:stable]
            self._emitted = stable
            return piece
        return None

    def flush(self) -> Optional[str]:
        """Emit any held-back tail (end of stream)."""
        if self._emitted < len(self.text):
            piece = self.text[self._emitted:]
            self._emitted = len(self.text)
            return piece
        return None


def load_tokenizer(model_cfg, tokenizer_path: Optional[str]) -> Tokenizer:
    """Pick the tokenizer for a model config: HF file when provided/found,
    byte-level for toy models."""
    if tokenizer_path:
        p = Path(tokenizer_path)
        if p.is_dir():
            p = p / "tokenizer.json"
        return HFTokenizer(p, model_cfg.bos_id, model_cfg.eos_ids, model_cfg.pad_id)
    if model_cfg.name.startswith("toy"):
        return ByteTokenizer()
    raise FileNotFoundError(
        f"No TOKENIZER_PATH configured for model {model_cfg.name!r} "
        "(set TOKENIZER_PATH to a tokenizer.json or checkpoint dir)"
    )
