"""Inference engine layer — the seam where the reference called OpenAI.

The reference's entire "model" was one awaited remote call
(app.py:117,184). Here that seam is the ``Engine`` protocol
(``protocol.py``), with implementations:

- ``fake.FakeEngine``     — deterministic rule-based engine for tests
- ``openai_compat.OpenAICompatEngine`` — httpx client for the reference's
  remote path (BASELINE config 1)
- ``jax_engine.JaxEngine`` — the TPU-native local engine: tokenizer →
  batcher → jit prefill/decode → Pallas kernels → sharded weights/KV
"""

from .protocol import Engine, EngineResult, EngineUnavailable, GenerationTimeout  # noqa: F401
