"""Radix tree over token prefixes, backed by the block-paged KV pool.

SGLang's RadixAttention sharing model over this repo's TPU pool layout
(engine/kv_pool.py): the tree maps token prefixes to the pool blocks
holding their KV, so N concurrent users sharing the system prompt cost
one block set, and turn N+1 of a multi-turn ``/execute`` agent loop —
which re-sends its entire history — prefills only the unmatched suffix
instead of recomputing everything. This replaces the single-resident-
prefix ``engine/prefix_cache.py`` model in pool mode (the dense KV ladder
keeps the old PrefixKV splice).

Shape of the tree (page-granular trie + partial tails):

- Every edge is exactly ONE full page of tokens (``page`` ids), keyed by
  the page's token tuple; the node holds the pool block containing that
  page's KV. Node boundaries therefore always fall on page multiples, so
  a matched path maps straight into a slot's block table with zero
  copying — full blocks are shared read-only (decode never writes below
  a slot's live length) under one refcount each.
- A node may additionally hold one *tail*: a partial page (tokens, block,
  rows) — the remainder of the deepest inserted sequence below that
  node. A tail match cannot be shared in place (the new owner will write
  rows into that page as it decodes), so the caller copy-on-writes the
  matched rows into a fresh block (``BlockPool.note_cow``). One tail per
  node, latest-wins on divergence: tails exist for the agent-loop resume
  case, where the newest continuation is the one that returns.

Eviction is refcount-aware block reclamation, not whole-entry deletion:
the LRU walk drops childless nodes (tails first), decref'ing their blocks
— a block still mapped by a live slot survives at refcount >= 1 and only
its *cached* state ends. ``max_blocks`` bounds the tree's held blocks
(RADIX_LRU_BLOCKS); ``evict_for`` frees pool pressure on demand.

Two-tier demotion (ISSUE 20): with a ``HostBlockStore`` attached, the
eviction walk *demotes* a cold page to pinned host RAM (CRC32 stamped)
instead of discarding it — the node stays in the tree holding a host
block id (``_Node.host``) and no device block. Host-resident nodes form
bottom-hanging subtrees by construction: a node may give up its device
block only once ALL its children are host-resident (or it has none), and
``match`` promotes top-down, so a host node's parent is never below it.
``match`` transparently re-onloads host pages it walks into — verified
against the demote-time checksum; a corrupt or allocation-starved onload
ends the match there (the caller prefills the suffix — zero failed
requests, counted per cause), and a corrupt page's whole host subtree is
dropped. The LRU clock spans both tiers: when the host store is full,
host leaves older than the incoming demote are dropped first; an
incoming page older than every resident one is discarded, exactly the
single-tier behaviour. ``offload:fail`` / ``onload:corrupt`` drill
points (testing/faults.py) are consumed through the duck-typed
``faults`` hook so both engines inherit them.

Host-side, numpy/stdlib only; single-writer (scheduler thread / event
loop) like the pool itself.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from .kv_pool import BlockPool, HostBlockStore, alloc_with_evict


@dataclasses.dataclass
class MatchResult:
    """One admission's view of a prefix match.

    ``blocks`` are full shared pages, already incref'd FOR THE CALLER
    (map them into the slot table as-is). ``tail_block``/``tail_rows``
    name a partial page whose first ``tail_rows`` KV rows match — also
    incref'd; the caller must copy those rows into a fresh block and
    ``decref([tail_block])`` once the copy has executed. ``n_tokens`` =
    matched tokens total (full pages + tail rows)."""

    n_tokens: int = 0
    blocks: List[int] = dataclasses.field(default_factory=list)
    tail_block: Optional[int] = None
    tail_rows: int = 0


class _Node:
    __slots__ = ("children", "block", "host", "tail", "parent", "key",
                 "last")

    def __init__(self, parent: Optional["_Node"], key: Optional[tuple],
                 block: Optional[int]):
        self.children: Dict[tuple, _Node] = {}
        self.block = block           # pool block of this node's page
        # Host tier (ISSUE 20): exactly one of block/host is set for a
        # non-root node. host is the HostBlockStore id of the demoted
        # page; block is None while host-resident.
        self.host: Optional[int] = None
        self.parent = parent
        self.key = key               # page token tuple (None at root)
        # (tokens tuple, block id, rows) — the partial page below this
        # node, or None. Tails are never demoted (a partial page is the
        # least shareable KV — it drops first instead).
        self.tail: Optional[Tuple[tuple, int, int]] = None
        self.last = 0                # LRU stamp (monotonic, BOTH tiers)


class RadixCache:
    def __init__(self, pool: BlockPool, *, max_blocks: int = 0,
                 host_store: Optional[HostBlockStore] = None,
                 offload_fn=None, onload_fn=None, faults=None):
        self.pool = pool
        self.page = pool.page
        # Host tier (ISSUE 20): demote target for cold pages. offload_fn
        # (block -> np.ndarray) reads the page's device KV at demote;
        # onload_fn(block, data) writes it back at promote. The fake
        # engine passes neither — its payload is the page's token tuple,
        # so the checksum round-trip is still real. ``faults`` is the
        # duck-typed injector view (offload_fail()/onload_corrupt()).
        self.host_store = host_store if (
            host_store is not None and host_store.capacity > 0) else None
        self.offload_fn = offload_fn
        self.onload_fn = onload_fn
        self.faults = faults
        # hbid -> node holding it (exactly one — the host-tier ownership
        # invariant HostBlockStore.check asserts).
        self._host_nodes: Dict[int, _Node] = {}
        # LRU stamp of the match walk currently in flight: eviction
        # triggered by a mid-walk promote must never demote/drop the
        # walk's own path (the recorded blocks are incref'd in bulk only
        # at the end). 0 = no walk in flight.
        self._protect_stamp = 0
        # True while clear() drains: the reset condemns cached KV, so
        # eviction must plain-drop, never demote it into the host store.
        self._demote_suspended = False
        # 0 = auto: a quarter of the pool may sit cached — enough to keep
        # the system prompt + recent agent histories hot without starving
        # live admissions.
        self.max_blocks = int(max_blocks) if max_blocks > 0 \
            else max(1, pool.n_blocks // 4)
        self._root = _Node(None, None, None)
        self._clock = itertools.count(1)
        # block id -> number of tree edges holding it (a block can be
        # cached both as a node's page and as a tail while a sequence
        # grows through it; each edge carries its own pool ref).
        self._held: Dict[int, int] = {}
        # Maintained node counter: /health reads stats() from the HTTP
        # thread while the scheduler mutates the tree, so the cheap
        # surfaces must never WALK it (a DFS racing an insert raises
        # "dict changed size during iteration").
        self._nodes = 0
        self.hit_tokens_total = 0
        self.miss_tokens_total = 0
        self.insertions_total = 0
        self.evicted_blocks_total = 0

    def carry_counters(self, prev: "RadixCache") -> None:
        """Inherit cumulative counters across an engine reset (same
        rationale as BlockPool.carry_counters — the /metrics
        delta-mirror must never see totals go backwards)."""
        self.hit_tokens_total = prev.hit_tokens_total
        self.miss_tokens_total = prev.miss_tokens_total
        self.insertions_total = prev.insertions_total
        self.evicted_blocks_total = prev.evicted_blocks_total

    # ------------------------------------------------------------- match

    def cached_block_count(self) -> int:
        return len(self._held)          # len() is atomic under the GIL

    def cached_blocks(self) -> Set[int]:
        """Snapshot of the tree-held block set. Safe to call from a
        NON-scheduler thread (/health, /metrics): copying a dict's keys
        while the owner resizes it can raise RuntimeError — retry, and
        degrade to empty rather than 500 the probe (the scrape is a
        gauge, not an invariant check)."""
        for _ in range(4):
            try:
                return set(self._held)
            except RuntimeError:        # pragma: no cover - racy resize
                continue
        return set()                    # pragma: no cover - racy resize

    def _hold(self, block: int) -> None:
        self.pool.incref([block])
        self._held[block] = self._held.get(block, 0) + 1

    def _release(self, block: int) -> None:
        n = self._held.get(block, 0) - 1
        if n <= 0:
            self._held.pop(block, None)
        else:
            self._held[block] = n
        self.pool.decref([block])
        self.evicted_blocks_total += 1

    def node_count(self) -> int:
        return self._nodes              # maintained, never a tree walk

    def match(self, ids: Sequence[int]) -> MatchResult:
        """Longest cached prefix of ``ids``: full pages walked exactly,
        then at most one partial-tail match. Matched blocks are incref'd
        for the caller (see MatchResult). Counters: ``hit_tokens_total``
        gains the match, ``miss_tokens_total`` the unmatched remainder.

        Host-resident pages on the path are transparently promoted
        (checksum-verified onload, ISSUE 20); a failed promote — device
        tier full even after eviction, or a corrupt host copy — ends the
        match there and the caller prefills the suffix, so the host tier
        can degrade a hit into a prefill but never fail a request."""
        page = self.page
        node, n = self._root, 0
        blocks: List[int] = []
        stamp = next(self._clock)
        node.last = stamp
        self._protect_stamp = stamp
        try:
            while len(ids) - n >= page:
                child = node.children.get(tuple(ids[n:n + page]))
                if child is None:
                    break
                child.last = stamp
                if child.block is None and not self._promote(child):
                    break
                blocks.append(child.block)
                node = child
                n += page
        finally:
            self._protect_stamp = 0
        tail_block, tail_rows = None, 0
        if node.tail is not None:
            t_tokens, t_block, t_rows = node.tail
            limit = min(t_rows, len(ids) - n)
            common = 0
            while common < limit and t_tokens[common] == ids[n + common]:
                common += 1
            if common > 0:
                tail_block, tail_rows = t_block, common
        matched = n + tail_rows
        self.hit_tokens_total += matched
        self.miss_tokens_total += len(ids) - matched
        if blocks:
            self.pool.incref(blocks)
            self.pool.note_shared(len(blocks))
        if tail_block is not None:
            self.pool.incref([tail_block])
        return MatchResult(n_tokens=matched, blocks=blocks,
                           tail_block=tail_block, tail_rows=tail_rows)

    # ------------------------------------------------------------ insert

    def insert(self, ids: Sequence[int], blocks: Sequence[int]) -> int:
        """Cache the chain ``ids`` whose KV lives in ``blocks`` (block i
        holds rows [i*page, (i+1)*page) of the sequence; the last block
        may be partial). The tree takes its OWN refs on blocks it newly
        caches — the caller's refs are untouched (a finishing slot
        releases its table afterwards and shared blocks decay to
        cached). Existing nodes on the path are reused (their resident
        block stays; the caller's duplicate KV for that page is simply
        not cached). Returns the number of blocks newly cached."""
        page = self.page
        if len(blocks) < pages_needed(len(ids), page):
            raise ValueError(
                f"chain of {len(ids)} tokens needs "
                f"{pages_needed(len(ids), page)} blocks, got {len(blocks)}")
        node, taken = self._root, 0
        stamp = next(self._clock)
        node.last = stamp
        full = len(ids) // page
        for i in range(full):
            key = tuple(ids[i * page:(i + 1) * page])
            child = node.children.get(key)
            if child is None:
                b = blocks[i]
                self._hold(b)
                child = _Node(node, key, b)
                node.children[key] = child
                self._nodes += 1
                taken += 1
            elif child.block is None:
                # Host-resident page on the insert path (ISSUE 20): the
                # caller just decoded through this page, so its device
                # block carries the same KV — adopt it and free the host
                # copy (a promotion that costs no onload).
                self._adopt(child, blocks[i])
                taken += 1
            child.last = stamp
            node = child
        rows = len(ids) % page
        if rows:
            t_tokens = tuple(ids[full * page:])
            b = blocks[full]
            cur = node.tail
            keep_existing = (
                cur is not None and len(cur[0]) >= rows
                and cur[0][:rows] == t_tokens)
            if keep_existing:
                pass             # the resident tail already covers this one
            elif cur is not None and cur[1] == b:
                # Same physical block, longer/different rows (a preempted
                # slot finishing re-inserts its own tail): the tree's ref
                # already covers it — just update the view.
                node.tail = (t_tokens, b, rows)
            else:
                self._hold(b)
                if cur is not None:
                    self._drop_tail(node)
                node.tail = (t_tokens, b, rows)
                taken += 1
        self.insertions_total += 1
        self.enforce_budget()
        return taken

    def _drop_tail(self, node: _Node) -> None:
        if node.tail is None:
            return
        _, b, _ = node.tail
        node.tail = None
        self._release(b)

    # ---------------------------------------------------------- eviction

    def _protected(self, node: _Node) -> bool:
        """Is ``node`` on the match walk currently in flight? Promotion
        can trigger eviction mid-walk (alloc_with_evict); the walk's own
        path — every node stamped with the walk's clock value — must
        survive it, since the caller's bulk incref happens only at the
        end of the match."""
        return self._protect_stamp > 0 and node.last >= self._protect_stamp

    def _demotable(self, node: _Node) -> bool:
        """May ``node`` give up its device block? Only once no descendant
        chain still needs it: all children host-resident (or none), no
        tail, not the walk-protected path. An interior eviction would
        orphan device descendants' chains — but a node whose entire
        subtree already lives in the host tier hangs at the bottom of the
        device tree, so demoting/dropping it keeps both tiers coherent."""
        return (node is not self._root and node.parent is not None
                and node.tail is None and node.block is not None
                and not self._protected(node)
                and all(c.block is None for c in node.children.values()))

    def _evictables(self) -> List[Tuple[int, int, _Node]]:
        """(last, kind, node) for every droppable unit, LRU-first. Tails
        rank before their node's block (kind 0 < 1) so partial pages —
        the least shareable KV — reclaim first at equal recency; only
        nodes passing ``_demotable`` may drop their block (an interior
        eviction would orphan descendants' chains)."""
        out: List[Tuple[int, int, _Node]] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.tail is not None and not self._protected(node):
                out.append((node.last, 0, node))
            if self._demotable(node):
                out.append((node.last, 1, node))
        out.sort(key=lambda t: (t[0], t[1]))
        return out

    def _drop_node(self, node: _Node) -> None:
        del node.parent.children[node.key]
        self._nodes -= 1
        self._release(node.block)
        if node.children:
            # All host-resident (the _demotable precondition): dropping
            # this interior node orphans its host subtree — purge it so
            # the host store never holds unreachable pages.
            for child in list(node.children.values()):
                self._purge_host_subtree(child)
            node.children = {}

    def _evict_until(self, done) -> bool:
        """Evict strictly-LRU units until ``done()``: one evictables
        collection seeds a heap, and dropping a node lazily pushes its
        parent once it becomes droppable — O((n + evictions)·log n),
        not the O(n²) a full re-collect per block would cost on the
        scheduler hot path, while preserving exact LRU order (a freed
        leaf's OLDER parent must evict before a younger sibling chain).
        With a host store attached, "evict" means demote-to-host where
        the page qualifies (cold, unmapped, store has or can make room)
        and plain drop otherwise — either way the device block frees.
        Returns False once nothing evictable remains."""
        if done():
            return True
        heap = [(last, kind, i, node)
                for i, (last, kind, node) in enumerate(self._evictables())]
        heapq.heapify(heap)
        seq = len(heap)                  # tie-break for lazy pushes
        while not done():
            while heap:
                _, kind, _, node = heapq.heappop(heap)
                # Staleness: a unit may have been consumed by an earlier
                # drop in this run (e.g. its tail went first).
                if kind == 0:
                    if node.tail is None or self._protected(node):
                        continue
                    self._drop_tail(node)
                    if self._demotable(node):
                        # The tail was the node's last blocker — its
                        # block itself is evictable now.
                        heapq.heappush(heap, (node.last, 1, seq, node))
                        seq += 1
                else:
                    if (not self._demotable(node)
                            or node.parent.children.get(node.key)
                            is not node):
                        continue
                    parent = node.parent
                    if not self._demote_node(node):
                        self._drop_node(node)
                    if self._demotable(parent):
                        heapq.heappush(heap,
                                       (parent.last, 1, seq, parent))
                        seq += 1
                break
            else:
                return False             # heap drained, target unmet
        return True

    # ------------------------------------------------- host tier (ISSUE 20)

    def _fault(self, name: str) -> bool:
        """Consume a one-shot drill point off the duck-typed injector
        view (offload_fail / onload_corrupt); False when no injector or
        the point is not armed."""
        fn = getattr(self.faults, name, None)
        return bool(fn()) if callable(fn) else False

    def _page_payload(self, node: _Node) -> np.ndarray:
        """The bytes that travel to the host tier for one page: the
        device KV rows when an offload_fn is wired (jax batcher), else
        the page's token tuple (fake engine) — fictional KV, but a real
        checksum round-trip either way."""
        if self.offload_fn is not None:
            return np.asarray(self.offload_fn(node.block))
        return np.asarray(node.key, dtype=np.int64)

    def _oldest_host_leaf(self, max_last: int) -> Optional[_Node]:
        """LRU victim for host-store room-making: the stalest host leaf
        no younger than ``max_last`` (the incoming demote's stamp — the
        LRU spans both tiers, so a page colder than everything resident
        is discarded rather than displacing warmer pages)."""
        best: Optional[_Node] = None
        for cand in self._host_nodes.values():
            if cand.children or self._protected(cand):
                continue
            if cand.last > max_last:
                continue
            if best is None or cand.last < best.last:
                best = cand
        return best

    def _drop_host_leaf(self, node: _Node) -> None:
        del node.parent.children[node.key]
        self._nodes -= 1
        self.host_store.free(node.host)
        self._host_nodes.pop(node.host, None)
        self.host_store.note_dropped()
        node.host = None

    def _purge_host_subtree(self, node: _Node) -> None:
        """Free every host page under (and including) ``node``, which is
        already detached from its parent — used when an interior drop or
        a corrupt onload invalidates the whole chain below a point."""
        stack = [node]
        while stack:
            cur = stack.pop()
            stack.extend(cur.children.values())
            cur.children = {}
            self._nodes -= 1
            if cur.tail is not None:     # pragma: no cover - defensive
                self._drop_tail(cur)
                self._nodes += 1         # _drop_tail is not a node drop
            if cur.host is not None:
                self.host_store.free(cur.host)
                self._host_nodes.pop(cur.host, None)
                self.host_store.note_dropped()
                cur.host = None
            elif cur.block is not None:  # pragma: no cover - defensive
                self._release(cur.block)

    def _demote_node(self, node: _Node) -> bool:
        """Device→host demotion of one cold page: copy the page payload
        into the pinned host store (CRC32 stamped by ``put``), release
        the device block, and keep the node in the tree host-resident.
        Returns False when the page must be plain-dropped instead — host
        tier off, the block still mapped by a live slot (demoting would
        free no HBM), the ``offload:fail`` drill, or a store full of
        strictly warmer pages."""
        store = self.host_store
        if store is None or self._demote_suspended:
            return False
        if self.pool.ref(node.block) != 1:
            return False
        if self._fault("offload_fail"):
            store.offload_fail_total += 1
            return False
        while store.free_count < 1:
            victim = self._oldest_host_leaf(node.last)
            if victim is None:
                store.note_dropped()
                return False
            self._drop_host_leaf(victim)
        data = self._page_payload(node)
        hbid = store.put(data)
        node.host = hbid
        self._host_nodes[hbid] = node
        b = node.block
        node.block = None
        # The tree's device hold ends; ref==1 (checked above) means the
        # block actually frees. Not an eviction for counting purposes —
        # the page survives, demoted_total tracks it.
        n = self._held.get(b, 0) - 1
        if n <= 0:
            self._held.pop(b, None)
        else:                            # pragma: no cover - defensive
            self._held[b] = n
        self.pool.decref([b])
        return True

    def _promote(self, node: _Node) -> bool:
        """Host→device promotion during a match walk: verify the page
        against its demote-time checksum, allocate a device block (with
        eviction backpressure — which may itself demote colder pages),
        onload, and hand the alloc's ref to the tree. On a corrupt page
        the node AND its host subtree drop (nothing below a bad page can
        be trusted); on allocation failure the host copy is kept for a
        later, less-pressured attempt. Either failure returns False —
        the match ends there and the caller prefills the suffix."""
        store = self.host_store
        hbid = node.host
        data = store.get(hbid)
        if self._fault("onload_corrupt"):
            # Flip one byte of a COPY of the payload: the real verify
            # path catches it, exactly as bit-rot in host RAM would.
            raw = bytearray(np.ascontiguousarray(data).tobytes())
            if raw:
                raw[0] ^= 0xFF
            data = np.frombuffer(
                bytes(raw), dtype=data.dtype).reshape(data.shape)
        if not store.verify(hbid, data):
            store.note_onload_fail("corrupt")
            del node.parent.children[node.key]
            self._purge_host_subtree(node)
            return False
        dev = alloc_with_evict(self.pool, self, 1)
        if dev is None:
            store.note_onload_fail("exhausted")
            return False
        b = dev[0]
        if self.onload_fn is not None:
            self.onload_fn(b, data)
        store.free(hbid)
        store.onloaded_total += 1
        self._host_nodes.pop(hbid, None)
        node.host = None
        node.block = b
        # alloc's refcount-1 becomes the tree's hold (no extra incref);
        # the caller's ref rides the match's bulk incref like any other
        # matched page.
        self._held[b] = self._held.get(b, 0) + 1
        return True

    def _adopt(self, node: _Node, block: int) -> None:
        """Insert-path promotion: the caller's device block already
        carries this page's KV, so the host copy is redundant — take the
        tree's own ref on the device block and free the host page."""
        self.host_store.free(node.host)
        self.host_store.adopted_total += 1
        self._host_nodes.pop(node.host, None)
        node.host = None
        node.block = block
        self._hold(block)

    def host_holders(self) -> Dict[int, int]:
        """Host-tier holder map for the cross-tier exact-balance check
        (each resident host block is held by exactly one node)."""
        return {hbid: 1 for hbid in self._host_nodes}

    def host_resident_blocks(self) -> int:
        return len(self._host_nodes)

    def enforce_budget(self) -> None:
        self._evict_until(lambda: len(self._held) <= self.max_blocks)

    def evict_for(self, n_free: int) -> bool:
        """Free pool pressure: evict LRU cached blocks until the pool has
        ``n_free`` free blocks or nothing cached remains. Returns True if
        the target was met. Evicting a block still mapped by a live slot
        drops only the CACHED state (refcount stays > 0) — it keeps
        evicting until actual free blocks materialize."""
        return self._evict_until(lambda: self.pool.free_count >= n_free)

    def clear(self) -> None:
        """Drop every cached block in BOTH tiers (engine reset: the
        pool's device arrays are being rebuilt and host copies of a
        possibly-poisoned generation cannot be trusted either, so the
        containment reset rebuilds the whole two-tier world). Demotion
        is suspended for the drain — clearing into the host store would
        smuggle condemned KV across the reset."""
        self._demote_suspended = True
        try:
            self._evict_until(lambda: not self._held and not self._nodes)
        finally:
            self._demote_suspended = False
        if self.host_store is not None:
            for hbid in list(self._host_nodes):
                self.host_store.free(hbid)
                self.host_store.note_dropped()
        self._host_nodes.clear()
        self._root = _Node(None, None, None)
        self._nodes = 0

    def stats(self) -> dict:
        body = {
            "nodes": self.node_count(),
            "cached_blocks": len(self._held),
            "max_blocks": self.max_blocks,
            "hit_tokens": self.hit_tokens_total,
            "miss_tokens": self.miss_tokens_total,
            "insertions": self.insertions_total,
            "evicted_blocks": self.evicted_blocks_total,
        }
        if self.host_store is not None:
            body["host_resident_nodes"] = len(self._host_nodes)
        return body


def pages_needed(n_tokens: int, page: int) -> int:
    return -(-max(0, n_tokens) // page)
