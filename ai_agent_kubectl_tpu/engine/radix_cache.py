"""Radix tree over token prefixes, backed by the block-paged KV pool.

SGLang's RadixAttention sharing model over this repo's TPU pool layout
(engine/kv_pool.py): the tree maps token prefixes to the pool blocks
holding their KV, so N concurrent users sharing the system prompt cost
one block set, and turn N+1 of a multi-turn ``/execute`` agent loop —
which re-sends its entire history — prefills only the unmatched suffix
instead of recomputing everything. This replaces the single-resident-
prefix ``engine/prefix_cache.py`` model in pool mode (the dense KV ladder
keeps the old PrefixKV splice).

Shape of the tree (page-granular trie + partial tails):

- Every edge is exactly ONE full page of tokens (``page`` ids), keyed by
  the page's token tuple; the node holds the pool block containing that
  page's KV. Node boundaries therefore always fall on page multiples, so
  a matched path maps straight into a slot's block table with zero
  copying — full blocks are shared read-only (decode never writes below
  a slot's live length) under one refcount each.
- A node may additionally hold one *tail*: a partial page (tokens, block,
  rows) — the remainder of the deepest inserted sequence below that
  node. A tail match cannot be shared in place (the new owner will write
  rows into that page as it decodes), so the caller copy-on-writes the
  matched rows into a fresh block (``BlockPool.note_cow``). One tail per
  node, latest-wins on divergence: tails exist for the agent-loop resume
  case, where the newest continuation is the one that returns.

Eviction is refcount-aware block reclamation, not whole-entry deletion:
the LRU walk drops childless nodes (tails first), decref'ing their blocks
— a block still mapped by a live slot survives at refcount >= 1 and only
its *cached* state ends. ``max_blocks`` bounds the tree's held blocks
(RADIX_LRU_BLOCKS); ``evict_for`` frees pool pressure on demand.

Host-side, numpy/stdlib only; single-writer (scheduler thread / event
loop) like the pool itself.
"""

from __future__ import annotations

import dataclasses
import heapq
import itertools
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .kv_pool import BlockPool


@dataclasses.dataclass
class MatchResult:
    """One admission's view of a prefix match.

    ``blocks`` are full shared pages, already incref'd FOR THE CALLER
    (map them into the slot table as-is). ``tail_block``/``tail_rows``
    name a partial page whose first ``tail_rows`` KV rows match — also
    incref'd; the caller must copy those rows into a fresh block and
    ``decref([tail_block])`` once the copy has executed. ``n_tokens`` =
    matched tokens total (full pages + tail rows)."""

    n_tokens: int = 0
    blocks: List[int] = dataclasses.field(default_factory=list)
    tail_block: Optional[int] = None
    tail_rows: int = 0


class _Node:
    __slots__ = ("children", "block", "tail", "parent", "key", "last")

    def __init__(self, parent: Optional["_Node"], key: Optional[tuple],
                 block: Optional[int]):
        self.children: Dict[tuple, _Node] = {}
        self.block = block           # pool block of this node's page
        self.parent = parent
        self.key = key               # page token tuple (None at root)
        # (tokens tuple, block id, rows) — the partial page below this
        # node, or None.
        self.tail: Optional[Tuple[tuple, int, int]] = None
        self.last = 0                # LRU stamp (monotonic counter)


class RadixCache:
    def __init__(self, pool: BlockPool, *, max_blocks: int = 0):
        self.pool = pool
        self.page = pool.page
        # 0 = auto: a quarter of the pool may sit cached — enough to keep
        # the system prompt + recent agent histories hot without starving
        # live admissions.
        self.max_blocks = int(max_blocks) if max_blocks > 0 \
            else max(1, pool.n_blocks // 4)
        self._root = _Node(None, None, None)
        self._clock = itertools.count(1)
        # block id -> number of tree edges holding it (a block can be
        # cached both as a node's page and as a tail while a sequence
        # grows through it; each edge carries its own pool ref).
        self._held: Dict[int, int] = {}
        # Maintained node counter: /health reads stats() from the HTTP
        # thread while the scheduler mutates the tree, so the cheap
        # surfaces must never WALK it (a DFS racing an insert raises
        # "dict changed size during iteration").
        self._nodes = 0
        self.hit_tokens_total = 0
        self.miss_tokens_total = 0
        self.insertions_total = 0
        self.evicted_blocks_total = 0

    def carry_counters(self, prev: "RadixCache") -> None:
        """Inherit cumulative counters across an engine reset (same
        rationale as BlockPool.carry_counters — the /metrics
        delta-mirror must never see totals go backwards)."""
        self.hit_tokens_total = prev.hit_tokens_total
        self.miss_tokens_total = prev.miss_tokens_total
        self.insertions_total = prev.insertions_total
        self.evicted_blocks_total = prev.evicted_blocks_total

    # ------------------------------------------------------------- match

    def cached_block_count(self) -> int:
        return len(self._held)          # len() is atomic under the GIL

    def cached_blocks(self) -> Set[int]:
        """Snapshot of the tree-held block set. Safe to call from a
        NON-scheduler thread (/health, /metrics): copying a dict's keys
        while the owner resizes it can raise RuntimeError — retry, and
        degrade to empty rather than 500 the probe (the scrape is a
        gauge, not an invariant check)."""
        for _ in range(4):
            try:
                return set(self._held)
            except RuntimeError:        # pragma: no cover - racy resize
                continue
        return set()                    # pragma: no cover - racy resize

    def _hold(self, block: int) -> None:
        self.pool.incref([block])
        self._held[block] = self._held.get(block, 0) + 1

    def _release(self, block: int) -> None:
        n = self._held.get(block, 0) - 1
        if n <= 0:
            self._held.pop(block, None)
        else:
            self._held[block] = n
        self.pool.decref([block])
        self.evicted_blocks_total += 1

    def node_count(self) -> int:
        return self._nodes              # maintained, never a tree walk

    def match(self, ids: Sequence[int]) -> MatchResult:
        """Longest cached prefix of ``ids``: full pages walked exactly,
        then at most one partial-tail match. Matched blocks are incref'd
        for the caller (see MatchResult). Counters: ``hit_tokens_total``
        gains the match, ``miss_tokens_total`` the unmatched remainder."""
        page = self.page
        node, n = self._root, 0
        blocks: List[int] = []
        stamp = next(self._clock)
        node.last = stamp
        while len(ids) - n >= page:
            child = node.children.get(tuple(ids[n:n + page]))
            if child is None:
                break
            blocks.append(child.block)
            node = child
            node.last = stamp
            n += page
        tail_block, tail_rows = None, 0
        if node.tail is not None:
            t_tokens, t_block, t_rows = node.tail
            limit = min(t_rows, len(ids) - n)
            common = 0
            while common < limit and t_tokens[common] == ids[n + common]:
                common += 1
            if common > 0:
                tail_block, tail_rows = t_block, common
        matched = n + tail_rows
        self.hit_tokens_total += matched
        self.miss_tokens_total += len(ids) - matched
        if blocks:
            self.pool.incref(blocks)
            self.pool.note_shared(len(blocks))
        if tail_block is not None:
            self.pool.incref([tail_block])
        return MatchResult(n_tokens=matched, blocks=blocks,
                           tail_block=tail_block, tail_rows=tail_rows)

    # ------------------------------------------------------------ insert

    def insert(self, ids: Sequence[int], blocks: Sequence[int]) -> int:
        """Cache the chain ``ids`` whose KV lives in ``blocks`` (block i
        holds rows [i*page, (i+1)*page) of the sequence; the last block
        may be partial). The tree takes its OWN refs on blocks it newly
        caches — the caller's refs are untouched (a finishing slot
        releases its table afterwards and shared blocks decay to
        cached). Existing nodes on the path are reused (their resident
        block stays; the caller's duplicate KV for that page is simply
        not cached). Returns the number of blocks newly cached."""
        page = self.page
        if len(blocks) < pages_needed(len(ids), page):
            raise ValueError(
                f"chain of {len(ids)} tokens needs "
                f"{pages_needed(len(ids), page)} blocks, got {len(blocks)}")
        node, taken = self._root, 0
        stamp = next(self._clock)
        node.last = stamp
        full = len(ids) // page
        for i in range(full):
            key = tuple(ids[i * page:(i + 1) * page])
            child = node.children.get(key)
            if child is None:
                b = blocks[i]
                self._hold(b)
                child = _Node(node, key, b)
                node.children[key] = child
                self._nodes += 1
                taken += 1
            child.last = stamp
            node = child
        rows = len(ids) % page
        if rows:
            t_tokens = tuple(ids[full * page:])
            b = blocks[full]
            cur = node.tail
            keep_existing = (
                cur is not None and len(cur[0]) >= rows
                and cur[0][:rows] == t_tokens)
            if keep_existing:
                pass             # the resident tail already covers this one
            elif cur is not None and cur[1] == b:
                # Same physical block, longer/different rows (a preempted
                # slot finishing re-inserts its own tail): the tree's ref
                # already covers it — just update the view.
                node.tail = (t_tokens, b, rows)
            else:
                self._hold(b)
                if cur is not None:
                    self._drop_tail(node)
                node.tail = (t_tokens, b, rows)
                taken += 1
        self.insertions_total += 1
        self.enforce_budget()
        return taken

    def _drop_tail(self, node: _Node) -> None:
        if node.tail is None:
            return
        _, b, _ = node.tail
        node.tail = None
        self._release(b)

    # ---------------------------------------------------------- eviction

    def _evictables(self) -> List[Tuple[int, int, _Node]]:
        """(last, kind, node) for every droppable unit, LRU-first. Tails
        rank before their node's block (kind 0 < 1) so partial pages —
        the least shareable KV — reclaim first at equal recency; only
        childless nodes may drop their block (an interior eviction would
        orphan descendants' chains)."""
        out: List[Tuple[int, int, _Node]] = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.tail is not None:
                out.append((node.last, 0, node))
            if node is not self._root and not node.children \
                    and node.tail is None:
                out.append((node.last, 1, node))
        out.sort(key=lambda t: (t[0], t[1]))
        return out

    def _drop_node(self, node: _Node) -> None:
        del node.parent.children[node.key]
        self._nodes -= 1
        self._release(node.block)

    def _evict_until(self, done) -> bool:
        """Evict strictly-LRU units until ``done()``: one evictables
        collection seeds a heap, and dropping a node lazily pushes its
        parent once it becomes childless — O((n + evictions)·log n),
        not the O(n²) a full re-collect per block would cost on the
        scheduler hot path, while preserving exact LRU order (a freed
        leaf's OLDER parent must evict before a younger sibling chain).
        Returns False once nothing evictable remains."""
        if done():
            return True
        heap = [(last, kind, i, node)
                for i, (last, kind, node) in enumerate(self._evictables())]
        heapq.heapify(heap)
        seq = len(heap)                  # tie-break for lazy pushes
        while not done():
            while heap:
                _, kind, _, node = heapq.heappop(heap)
                # Staleness: a unit may have been consumed by an earlier
                # drop in this run (e.g. its tail went first).
                if kind == 0:
                    if node.tail is None:
                        continue
                    self._drop_tail(node)
                    if node is not self._root and not node.children:
                        # The tail was the node's last droppable unit —
                        # its block itself is evictable now.
                        heapq.heappush(heap, (node.last, 1, seq, node))
                        seq += 1
                else:
                    if (node.children or node.tail is not None
                            or node.parent is None
                            or node.parent.children.get(node.key)
                            is not node):
                        continue
                    parent = node.parent
                    self._drop_node(node)
                    if (parent is not self._root and not parent.children
                            and parent.tail is None):
                        heapq.heappush(heap,
                                       (parent.last, 1, seq, parent))
                        seq += 1
                break
            else:
                return False             # heap drained, target unmet
        return True

    def enforce_budget(self) -> None:
        self._evict_until(lambda: len(self._held) <= self.max_blocks)

    def evict_for(self, n_free: int) -> bool:
        """Free pool pressure: evict LRU cached blocks until the pool has
        ``n_free`` free blocks or nothing cached remains. Returns True if
        the target was met. Evicting a block still mapped by a live slot
        drops only the CACHED state (refcount stays > 0) — it keeps
        evicting until actual free blocks materialize."""
        return self._evict_until(lambda: self.pool.free_count >= n_free)

    def clear(self) -> None:
        """Drop every cached block (engine reset: the pool's device
        arrays are being rebuilt, so cached KV is invalid)."""
        self._evict_until(lambda: not self._held and self._nodes == 0)
        self._root = _Node(None, None, None)
        self._nodes = 0

    def stats(self) -> dict:
        return {
            "nodes": self.node_count(),
            "cached_blocks": len(self._held),
            "max_blocks": self.max_blocks,
            "hit_tokens": self.hit_tokens_total,
            "miss_tokens": self.miss_tokens_total,
            "insertions": self.insertions_total,
            "evicted_blocks": self.evicted_blocks_total,
        }


def pages_needed(n_tokens: int, page: int) -> int:
    return -(-max(0, n_tokens) // page)
