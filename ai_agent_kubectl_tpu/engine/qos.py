"""Deadline-aware QoS ring: tenant/lane classification, fair-share
admission, and the brownout controller (ISSUE 7).

The serving path used to admit through a single FIFO ``queue.Queue`` —
one tenant flooding ``/kubectl-command`` starved every other client, and
an interactive request queued behind a 60-turn ``/execute`` agent loop.
SGLang's lesson (PAPERS.md) is that scheduler *policy*, not kernels, is
what keeps a multi-tenant LLM service live under contention. This module
is that policy layer, engine-agnostic and host-side:

- **Lanes** — every request runs in one of three priority lanes
  (``interactive`` > ``batch`` > ``background``). The lane comes from
  the tenant's configured tier (``TENANT_TIERS``) or an ``X-Priority``
  header, clamped so a client can never claim a higher lane than its
  tier allows.
- **Tenants** — the fair-share unit: the API key when one is presented,
  else the client IP (``classify``). Tenants are queue-internal only —
  they never become metric labels (unbounded cardinality).
- **QoSQueue** — weighted deficit-round-robin over per-tenant sub-queues
  (weights by lane), with per-tenant in-queue caps (429 to the flooding
  tenant, not 503 to everyone), expired-deadline purge at scan time
  (``queue_expired_total`` — an expired request must not occupy
  MAX_QUEUE_DEPTH until popped), and shed decisions that prefer the
  flooding tenant (a quiet tenant arriving at a full queue displaces the
  dominant tenant's newest request instead of being shed itself).
- **BrownoutController** — AIMD trim of effective per-lane concurrency:
  when interactive queue-wait p95 breaches ``SLO_INTERACTIVE_MS``,
  background's slot share halves first (then batch); recovery is
  additive, batch first, background last. The level is metric-visible
  (``qos_brownout_level``). Shares floor at one slot so brownout trims
  but never starves a lane outright.

The engine schedulers (``engine/batcher.py``, ``engine/fake.py``) own
the *mechanism* — preemptive decode via the PR 6 export/replay path
rides there; this module owns classification and queue policy so both
engines (and the fleet router) can never disagree on what "fair" means.
"""

from __future__ import annotations

import dataclasses
import queue as _queue
import threading
import time
from collections import OrderedDict, deque
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Callable, Deque, Dict, List, Optional, Tuple

#: the closed lane set, lowest priority first. Fixed here so lane names
#: can be Prometheus labels with cardinality bounded by construction.
LANE_BACKGROUND = "background"
LANE_BATCH = "batch"
LANE_INTERACTIVE = "interactive"
LANES = (LANE_BACKGROUND, LANE_BATCH, LANE_INTERACTIVE)
LANE_RANK = {lane: i for i, lane in enumerate(LANES)}
#: highest-priority-first iteration order (credit spending, preemption).
LANES_DESC = tuple(reversed(LANES))

#: default WDRR weights — one full round of a saturated queue serves
#: 8 interactive : 4 batch : 1 background.
DEFAULT_LANE_WEIGHTS = {LANE_INTERACTIVE: 8, LANE_BATCH: 4,
                        LANE_BACKGROUND: 1}

#: tenant key when no API key and no client address is known (direct
#: engine calls, tests) — one shared fair-share bucket.
ANON_TENANT = "anon"


def lane_rank(lane: Optional[str]) -> int:
    """Rank of a (possibly unknown) lane name; unknown ranks lowest so a
    corrupt lane string can never outrank real traffic."""
    return LANE_RANK.get(lane or "", -1)


def parse_lane_weights(spec: str) -> Dict[str, int]:
    """``"interactive:8,batch:4,background:1"`` → weight map. Missing
    lanes keep their defaults; a typo'd lane or weight is a startup
    error, not a silently skewed scheduler."""
    weights = dict(DEFAULT_LANE_WEIGHTS)
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        lane, sep, w = item.partition(":")
        lane = lane.strip().lower()
        if not sep or lane not in LANES:
            raise ValueError(
                f"LANE_WEIGHTS entry {item!r} must be lane:weight with "
                f"lane in {LANES}")
        weight = int(w)
        if weight < 1:
            raise ValueError(f"LANE_WEIGHTS weight must be >= 1, got {w}")
        weights[lane] = weight
    return weights


def parse_tenant_tiers(spec: str) -> Dict[str, str]:
    """``"keyA:interactive,10.0.0.5:background"`` → tenant-key → max-lane
    map (the *tier*: the highest lane that tenant may claim)."""
    tiers: Dict[str, str] = {}
    for item in (spec or "").split(","):
        item = item.strip()
        if not item:
            continue
        tenant, sep, lane = item.rpartition(":")
        lane = lane.strip().lower()
        if not sep or not tenant.strip() or lane not in LANES:
            raise ValueError(
                f"TENANT_TIERS entry {item!r} must be tenant:lane with "
                f"lane in {LANES}")
        tiers[tenant.strip()] = lane
    return tiers


@dataclasses.dataclass(frozen=True)
class QoSContext:
    """One request's QoS classification, carried from the HTTP layer to
    the engine scheduler on a contextvar (same pattern as obs.trace —
    it crosses awaits and task spawns, and the engine reads it once at
    submit time)."""

    tenant: str = ANON_TENANT
    lane: str = LANE_INTERACTIVE
    #: client-declared session identity (``X-Session-ID``, ISSUE 20):
    #: the unit the per-session token budget and the turn-N TTFT SLO
    #: account on. Empty = sessionless request (budget never applies).
    session: str = ""


_qos_var: ContextVar[Optional[QoSContext]] = ContextVar("qos_context",
                                                        default=None)


def current_qos() -> Optional[QoSContext]:
    return _qos_var.get()


@contextmanager
def use_qos(ctx: QoSContext):
    token = _qos_var.set(ctx)
    try:
        yield ctx
    finally:
        _qos_var.reset(token)


def classify(api_key: Optional[str], client_ip: Optional[str],
             priority_header: Optional[str],
             tiers: Dict[str, str],
             default_lane: str = LANE_INTERACTIVE,
             session: Optional[str] = None) -> QoSContext:
    """Tenant + lane for one request.

    Tenant: the API key when presented, else the client IP (the same
    identity the rate limiter buckets on). Lane: the ``X-Priority``
    request when valid, else the tenant's tier default — always clamped
    to the tier, so a client can *lower* its own priority freely (a
    polite bulk importer self-labels ``background``) but can never claim
    a lane above what its tier grants. ``session`` is the raw
    ``X-Session-ID`` header; it is namespaced under the tenant so one
    client can never spend (or observe) another tenant's budget by
    guessing its session string."""
    tenant = (api_key or "").strip() or (client_ip or "").strip() \
        or ANON_TENANT
    tier = tiers.get(tenant, default_lane)
    if tier not in LANES:
        tier = default_lane
    requested = (priority_header or "").strip().lower()
    lane = requested if requested in LANES else tier
    if lane_rank(lane) > lane_rank(tier):
        lane = tier
    sid = (session or "").strip()
    return QoSContext(tenant=tenant, lane=lane,
                      session=f"{tenant}/{sid}" if sid else "")


class SessionBudgets:
    """Per-session completion-token budgets (ISSUE 20).

    A multi-turn agent session is exactly the workload the two-tier KV
    cache accelerates — which also makes it the workload that can
    monopolize the engine (every turn re-admits radix-warm and wins the
    TTFT race against cold strangers). The budget is the counterweight:
    once a session has been *delivered* ``budget_tokens`` completion
    tokens, its later turns classify into the background lane. The
    session keeps working (lanes never starve outright — WDRR guarantees
    background a share) but stops outranking fresh interactive traffic.

    Accounting is delivered tokens (the billing ledger's unit), charged
    at finish by the engine scheduler — not at admission — so a shed or
    failed turn never burns budget. State is a bounded LRU keyed by the
    namespaced session id (``tenant/session``): at ``max_sessions`` the
    coldest session's counter is dropped, which *resets* that session's
    budget — the benign failure mode (a forgotten session regains
    priority) rather than an unbounded-memory one. ``budget_tokens <= 0``
    disables the whole mechanism. Thread-safe: charge runs on the
    scheduler thread, lane_for on the event loop."""

    def __init__(self, budget_tokens: int, *, max_sessions: int = 2048):
        self.budget_tokens = max(0, int(budget_tokens))
        self.max_sessions = max(1, int(max_sessions))
        self._lock = threading.Lock()
        self._spent: "OrderedDict[str, int]" = OrderedDict()
        self.demoted_total = 0
        self.evicted_total = 0

    @property
    def enabled(self) -> bool:
        return self.budget_tokens > 0

    def charge(self, session: str, tokens: int) -> None:
        """Add delivered completion tokens to a session's tally."""
        if not self.enabled or not session or tokens <= 0:
            return
        with self._lock:
            self._spent[session] = self._spent.get(session, 0) + int(tokens)
            self._spent.move_to_end(session)
            while len(self._spent) > self.max_sessions:
                self._spent.popitem(last=False)
                self.evicted_total += 1

    def over(self, session: str) -> bool:
        if not self.enabled or not session:
            return False
        with self._lock:
            return self._spent.get(session, 0) >= self.budget_tokens

    def lane_for(self, session: str, lane: str) -> str:
        """Clamp an over-budget session to the background lane (counted);
        requests already there pass through unchanged."""
        if lane != LANE_BACKGROUND and self.over(session):
            self.demoted_total += 1
            return LANE_BACKGROUND
        return lane

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            over = sum(1 for v in self._spent.values()
                       if v >= self.budget_tokens) if self.enabled else 0
            return {
                "enabled": self.enabled,
                "budget_tokens": self.budget_tokens,
                "sessions_tracked": len(self._spent),
                "sessions_over_budget": over,
                "demoted_total": self.demoted_total,
                "evicted_total": self.evicted_total,
            }


# TenantOverloaded lives in engine.protocol (it must subclass
# EngineOverloaded so the fleet's reroute arm and the breaker's
# overload-passthrough treat it as backpressure); re-exported here so
# QoS consumers have one import site.
from .protocol import TenantOverloaded  # noqa: E402


class QoSQueue:
    """Weighted deficit-round-robin admission queue over per-tenant
    sub-queues, grouped by lane.

    Drop-in for the batcher's ``queue.Queue`` surface (``put`` /
    ``get(timeout)`` / ``get_nowait`` / ``qsize`` / ``empty``, raising
    ``queue.Empty``), thread-safe (event-loop put, scheduler-thread
    get). Entries are the engines' request objects; the queue reads
    ``lane`` / ``tenant`` / ``deadline`` / ``cancel`` off them (missing
    attributes default to one interactive anon bucket — the pre-QoS
    behaviour) and stamps ``t_enqueue``.

    Policy in one place:

    - **WDRR**: each scheduling round grants every lane credit equal to
      its weight; pops spend credit highest-lane-first, so a saturated
      queue serves weights-proportionally per round with interactive
      served first within the round, and no lane ever starves.
    - **Per-tenant fairness**: within a lane, tenants round-robin
      (OrderedDict rotation); within a tenant, FIFO.
    - **Per-tenant cap**: a tenant with ``tenant_cap`` requests already
      queued is shed with :class:`TenantOverloaded` (HTTP 429 — the
      flooding tenant's problem, not everyone's).
    - **Flood-preferring displacement**: at global ``max_depth``, an
      arrival from a NON-dominant tenant displaces the dominant
      tenant's newest request at an equal-or-lower lane instead of
      being shed; the displaced requests are returned to the caller to
      error. An arrival from the dominant tenant itself sheds with the
      classic "admission queue full" EngineOverloaded.
    - **Scan-time expiry**: queue scans purge entries whose effective
      deadline passed (preempted-out time excluded via ``preempt_t0``)
      and count them (``expired_total``), calling ``on_expire`` so the
      engine can fail them with GenerationTimeout — an expired request
      stops occupying MAX_QUEUE_DEPTH the moment it is dead, not when
      it reaches the head.
    """

    #: background purge cadence during get() scans; puts at capacity
    #: always purge first (a full queue must shed live work only).
    PURGE_INTERVAL_SECS = 0.05

    def __init__(self, *, max_depth: int = 0, tenant_cap: int = 0,
                 weights: Optional[Dict[str, int]] = None,
                 on_expire: Optional[Callable] = None):
        self.max_depth = max(0, int(max_depth))
        # 0 = no per-tenant cap beyond the global depth.
        self.tenant_cap = max(0, int(tenant_cap))
        self.weights = dict(DEFAULT_LANE_WEIGHTS)
        if weights:
            self.weights.update(weights)
        self.on_expire = on_expire
        self._cond = threading.Condition()
        self._lanes: Dict[str, "OrderedDict[str, Deque]"] = {
            lane: OrderedDict() for lane in LANES}
        self._credit: Dict[str, float] = {lane: 0.0 for lane in LANES}
        self._size = 0
        self._last_purge = 0.0
        self.expired_total = 0
        self.displaced_total = 0

    # ------------------------------------------------------------ helpers

    @staticmethod
    def _lane_of(req) -> str:
        lane = getattr(req, "lane", None)
        return lane if lane in LANES else LANE_INTERACTIVE

    @staticmethod
    def _tenant_of(req) -> str:
        return getattr(req, "tenant", None) or ANON_TENANT

    @staticmethod
    def _effective_deadline(req) -> Optional[float]:
        """Deadline with preempted-out time excluded: a victim parked in
        the queue since ``preempt_t0`` gets that wall time back on
        resume (the engine credits it at admission), so the purge must
        judge it against the same extended deadline."""
        deadline = getattr(req, "deadline", None)
        if deadline is None:
            return None
        t0 = getattr(req, "preempt_t0", None)
        if t0 is not None:
            deadline += time.monotonic() - t0
        return deadline

    def _tenant_count(self, tenant: str) -> int:
        return sum(len(self._lanes[lane].get(tenant, ()))
                   for lane in LANES)

    # ------------------------------------------------------------ purging

    def _purge_locked(self, now: float, force: bool = False) -> None:
        if not force and now - self._last_purge < self.PURGE_INTERVAL_SECS:
            return
        self._last_purge = now
        expired: List = []
        for lane in LANES:
            tenants = self._lanes[lane]
            for tenant in list(tenants):
                dq = tenants[tenant]
                kept: Deque = deque()
                for req in dq:
                    cancel = getattr(req, "cancel", None)
                    if cancel is not None and cancel.is_set():
                        self._size -= 1      # client gone: drop silently
                        continue
                    deadline = self._effective_deadline(req)
                    if deadline is not None and now > deadline:
                        self._size -= 1
                        self.expired_total += 1
                        expired.append(req)
                        continue
                    kept.append(req)
                if kept:
                    tenants[tenant] = kept
                else:
                    del tenants[tenant]
        for req in expired:
            if self.on_expire is not None:
                try:
                    self.on_expire(req)
                except Exception:   # pragma: no cover - callback guard
                    pass

    # ------------------------------------------------------------- put

    def put(self, req) -> List:
        """Enqueue; returns requests displaced to make room (caller must
        fail them with an overload error). Raises
        :class:`TenantOverloaded` at the per-tenant cap and
        ``EngineOverloaded`` when the queue is full and this tenant is
        the one flooding it."""
        from .protocol import EngineOverloaded

        lane, tenant = self._lane_of(req), self._tenant_of(req)
        now = time.monotonic()
        displaced: List = []
        with self._cond:
            if (self.max_depth and self._size >= self.max_depth) or (
                    self.tenant_cap
                    and self._tenant_count(tenant) >= self.tenant_cap):
                # Make room from the dead before shedding the living.
                self._purge_locked(now, force=True)
            mine = self._tenant_count(tenant)
            if self.tenant_cap and mine >= self.tenant_cap:
                raise TenantOverloaded(
                    f"tenant queue cap reached ({mine}/{self.tenant_cap} "
                    f"queued for tenant {tenant!r}, lane {lane})",
                    tenant=tenant, lane=lane)
            if self.max_depth and self._size >= self.max_depth:
                victim = self._displacement_victim_locked(tenant, lane)
                if victim is None:
                    raise EngineOverloaded(
                        f"admission queue full "
                        f"({self._size}/{self.max_depth})")
                displaced.append(victim)
                self.displaced_total += 1
            req.t_enqueue = now
            tenants = self._lanes[lane]
            if tenant not in tenants:
                tenants[tenant] = deque()
            tenants[tenant].append(req)
            self._size += 1
            self._cond.notify()
        return displaced

    def _displacement_victim_locked(self, tenant: str, lane: str):
        """Shed decisions prefer the flooding tenant: the arriving
        request bumps the NEWEST queued request of the tenant holding
        the most queue share — but only when that tenant out-queues the
        arriver and the victim's lane doesn't outrank the arrival (a
        background request never displaces interactive work)."""
        counts: Dict[str, int] = {}
        for lane_q in self._lanes.values():
            for t, dq in lane_q.items():
                counts[t] = counts.get(t, 0) + len(dq)
        mine = counts.get(tenant, 0)
        fat = [(n, t) for t, n in counts.items() if t != tenant and n > mine]
        if not fat:
            return None
        fat.sort(reverse=True)
        arrival_rank = lane_rank(lane)
        for _, victim_tenant in fat:
            for victim_lane in LANES:        # lowest lane first
                if lane_rank(victim_lane) > arrival_rank:
                    break
                dq = self._lanes[victim_lane].get(victim_tenant)
                if not dq:
                    continue
                # Newest first, but NEVER a request that was already
                # admitted once (preempted victim / supervisor requeue,
                # carrying resume state): its client may already hold
                # streamed tokens, and shedding it would break the
                # byte-identical-completion contract.
                for i in range(len(dq) - 1, -1, -1):
                    req = dq[i]
                    if (getattr(req, "preempt_count", 0)
                            or getattr(req, "resume_ids", None)):
                        continue
                    del dq[i]
                    if not dq:
                        del self._lanes[victim_lane][victim_tenant]
                    self._size -= 1
                    return req
        return None

    def requeue_head(self, req) -> None:
        """Front-of-tenant-queue re-entry for preempted victims and
        supervisor requeues: never sheds, never counts against caps —
        the request was already admitted once."""
        lane, tenant = self._lane_of(req), self._tenant_of(req)
        with self._cond:
            req.t_enqueue = time.monotonic()
            tenants = self._lanes[lane]
            if tenant not in tenants:
                tenants[tenant] = deque()
                tenants.move_to_end(tenant, last=False)
            tenants[tenant].appendleft(req)
            self._size += 1
            self._cond.notify()

    # ------------------------------------------------------------- get

    def _pop_tenant_locked(self, lane: str):
        tenants = self._lanes[lane]
        tenant, dq = next(iter(tenants.items()))
        req = dq.popleft()
        if dq:
            tenants.move_to_end(tenant)      # round-robin across tenants
        else:
            del tenants[tenant]
        self._size -= 1
        return req

    def _pop_locked(self, exclude_lanes=(), min_lane: Optional[str] = None):
        self._purge_locked(time.monotonic())
        min_rank = lane_rank(min_lane) if min_lane else -1

        def available():
            return [lane for lane in LANES_DESC
                    if self._lanes[lane] and lane not in exclude_lanes
                    and lane_rank(lane) >= min_rank]

        avail = available()
        if not avail:
            return None
        # WDRR: spend this round's remaining credit highest-lane-first;
        # when every available lane's credit is spent, start a new round
        # (credit := weight). Empty lanes never accumulate credit across
        # rounds, so a lane waking after idling can't burst past its
        # share.
        for lane in avail:
            if self._credit[lane] >= 1.0:
                self._credit[lane] -= 1.0
                return self._pop_tenant_locked(lane)
        for lane in LANES:
            self._credit[lane] = float(self.weights[lane]) \
                if self._lanes[lane] else 0.0
        lane = avail[0]
        self._credit[lane] -= 1.0
        return self._pop_tenant_locked(lane)

    def get(self, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                req = self._pop_locked()
                if req is not None:
                    return req
                if deadline is None:
                    self._cond.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    # Condition timed out (or raced): one last try.
                    req = self._pop_locked()
                    if req is not None:
                        return req
                    raise _queue.Empty()

    def get_nowait(self, exclude_lanes=(), min_lane: Optional[str] = None):
        with self._cond:
            req = self._pop_locked(exclude_lanes, min_lane)
            if req is None:
                raise _queue.Empty()
            return req

    def drain(self) -> List:
        """Pop everything (shutdown paths), fairness-blind."""
        out: List = []
        with self._cond:
            for lane_q in self._lanes.values():
                for dq in lane_q.values():
                    out.extend(dq)
                lane_q.clear()
            self._size = 0
        return out

    # ------------------------------------------------------ observability

    def qsize(self) -> int:
        return self._size

    __len__ = qsize

    def empty(self) -> bool:
        return self._size == 0

    def lane_depths(self) -> Dict[str, int]:
        with self._cond:
            return {lane: sum(len(dq) for dq in self._lanes[lane].values())
                    for lane in LANES}

    def tenant_depths(self) -> Dict[str, int]:
        with self._cond:
            counts: Dict[str, int] = {}
            for lane_q in self._lanes.values():
                for t, dq in lane_q.items():
                    counts[t] = counts.get(t, 0) + len(dq)
            return counts

    def starved_lane(self, now: float, wait_secs: float,
                     exclude=()) -> Optional[str]:
        """Highest lane holding a request enqueued more than
        ``wait_secs`` ago — the preemption trigger. Judged on
        ``t_enqueue`` (stamped by put/requeue), so a just-preempted
        victim can't immediately read as starved itself; the whole
        deque is scanned, not just its head (a requeued victim's fresh
        stamp must not mask an older request queued behind it).
        ``exclude`` names lanes a preemption can't help (brownout-capped
        — a freed slot would be unadmittable for them anyway)."""
        with self._cond:
            for lane in LANES_DESC:
                if lane in exclude:
                    continue
                for dq in self._lanes[lane].values():
                    if any(now - getattr(req, "t_enqueue", now) > wait_secs
                           for req in dq):
                        return lane
        return None

    def stats(self) -> Dict:
        return {
            "lane_depth": self.lane_depths(),
            "expired": self.expired_total,
            "displaced": self.displaced_total,
            "tenants": len(self.tenant_depths()),
        }


class BrownoutController:
    """AIMD trim of effective per-lane decode concurrency.

    Interactive queue-wait samples feed a trailing window; when their
    p95 breaches ``slo_ms``, the controller multiplicatively halves
    background's slot share first, and only once background is at its
    floor does batch start shedding — "background sheds first".
    Recovery is additive and in the opposite order (batch first,
    background last), so a recovering service restores its paying lanes
    before its bulk lanes. ``slo_ms <= 0`` disables the controller
    (level stays 0, shares stay 1.0).

    The *engine scheduler* enforces the shares: lane slot caps are
    ``max(1, int(batch_size * share))`` — a brownout trims a lane's
    concurrency, it never zeroes it (the acceptance bar says no lane is
    ever starved outright).
    """

    #: multiplicative-decrease factor and additive-increase step.
    DECREASE = 0.5
    INCREASE = 0.125
    #: background must reach this floor before batch starts shedding.
    FLOOR = 0.25

    def __init__(self, slo_ms: float, *, window_secs: float = 10.0,
                 eval_interval_secs: float = 1.0):
        self.slo_ms = float(slo_ms)
        self.window_secs = window_secs
        self.eval_interval_secs = eval_interval_secs
        self.shares: Dict[str, float] = {LANE_BACKGROUND: 1.0,
                                         LANE_BATCH: 1.0}
        self._waits: Deque[Tuple[float, float]] = deque(maxlen=4096)
        self._last_eval = 0.0
        self.transitions = 0

    @property
    def level(self) -> int:
        """0 = no brownout, 1 = background trimmed, 2 = batch trimmed
        too (the metric-visible state)."""
        if self.shares[LANE_BATCH] < 1.0:
            return 2
        if self.shares[LANE_BACKGROUND] < 1.0:
            return 1
        return 0

    def note_queue_wait(self, lane: str, wait_ms: float,
                        now: Optional[float] = None) -> None:
        """Feed one admission's queue wait; only interactive waits drive
        the SLO (that's the lane the brownout protects)."""
        if lane != LANE_INTERACTIVE or self.slo_ms <= 0:
            return
        self._waits.append((time.monotonic() if now is None else now,
                            wait_ms))

    def _p95_locked(self, now: float) -> Optional[float]:
        horizon = now - self.window_secs
        while self._waits and self._waits[0][0] < horizon:
            self._waits.popleft()
        vals = sorted(w for _, w in self._waits)
        if not vals:
            return None
        return vals[min(len(vals) - 1, int(round(0.95 * (len(vals) - 1))))]

    def maybe_eval(self, now: Optional[float] = None,
                   burn_fn: Optional[Callable[[], Optional[float]]] = None
                   ) -> bool:
        """Time-gated AIMD step; returns True when the shares changed.
        Called from the scheduler loop — cheap when gated out.

        ``burn_fn`` is the SLO burn-rate input (obs/slo.py): evaluated
        only when the time gate passes, a fast-window burn rate > 1.0
        for interactive queue wait counts as a breach even while the
        raw p95 still sits under the threshold — the budget is being
        eaten faster than the objective allows, which is exactly when
        trimming bulk lanes early is cheaper than paging later. None
        (or a burn_fn returning None — no samples) keeps the classic
        p95-only behaviour."""
        if self.slo_ms <= 0:
            return False
        now = time.monotonic() if now is None else now
        if now - self._last_eval < self.eval_interval_secs:
            return False
        self._last_eval = now
        p95 = self._p95_locked(now)
        burn = burn_fn() if burn_fn is not None else None
        before = dict(self.shares)
        if (p95 is not None and p95 > self.slo_ms) or (
                burn is not None and burn > 1.0):
            if self.shares[LANE_BACKGROUND] > self.FLOOR:
                self.shares[LANE_BACKGROUND] = max(
                    self.FLOOR, self.shares[LANE_BACKGROUND] * self.DECREASE)
            else:
                self.shares[LANE_BATCH] = max(
                    self.FLOOR, self.shares[LANE_BATCH] * self.DECREASE)
        elif p95 is None or p95 < 0.8 * self.slo_ms:
            # Recover batch to full before background gets anything back.
            if self.shares[LANE_BATCH] < 1.0:
                self.shares[LANE_BATCH] = min(
                    1.0, self.shares[LANE_BATCH] + self.INCREASE)
            elif self.shares[LANE_BACKGROUND] < 1.0:
                self.shares[LANE_BACKGROUND] = min(
                    1.0, self.shares[LANE_BACKGROUND] + self.INCREASE)
        changed = self.shares != before
        if changed:
            self.transitions += 1
        return changed

    def lane_cap(self, lane: str, batch_size: int) -> int:
        """Effective slot cap for ``lane`` under the current shares.
        Interactive is never trimmed; trimmed lanes floor at one slot."""
        share = self.shares.get(lane)
        if share is None or share >= 1.0:
            return batch_size
        return max(1, int(batch_size * share))
