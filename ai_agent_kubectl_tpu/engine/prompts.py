"""Prompt construction (reference app.py:50-57).

The system persona is kept verbatim from the reference — it is also the
shared prefix that the engine's prefix-KV cache precomputes once and splices
ahead of every request (SURVEY.md §5, long-context row; BASELINE north
star).
"""

from __future__ import annotations

SYSTEM_PROMPT = """\
You are a Kubernetes CLI specialist.
When given a user request, output exactly one valid, single-line `kubectl` command that fulfils it.
Do not include comments, explanations, or shell operators (`;`, `&&`, `||`, (```) etc.).
Only output the command itself, nothing else.
"""

USER_TEMPLATE = "User Request: {query}\nKubectl Command:"


def render_prompt(query: str) -> str:
    """Full prompt = shared system prefix + per-request suffix."""
    return SYSTEM_PROMPT + USER_TEMPLATE.format(query=query)


def split_prompt(query: str) -> tuple[str, str]:
    """(shared_prefix, per_request_suffix) — the prefix half is what the
    prefix-KV cache keys on."""
    return SYSTEM_PROMPT, USER_TEMPLATE.format(query=query)
