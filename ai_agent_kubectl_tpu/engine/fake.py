"""FakeEngine — deterministic engine for tests (SURVEY.md §4, boundary 1).

Maps a handful of natural-language patterns to canned kubectl commands and
supports scripted responses/latency/failures so API tests can exercise every
status code without a TPU or network.

``FakeChunkedEngine`` (further down) is the decode-PIPELINE fake: a pure-
numpy twin of the batcher's chunked scheduler that serves deterministic
token streams through the SAME packed-chunk contract
(protocol.pack_chunk/unpack_chunk/consume_chunk_row) and a
CHUNK_PIPE_DEPTH-deep speculative pipeline — so depth sweeps, device-side
termination semantics, wasted-step accounting, and disconnect aborts are
testable in milliseconds, without a jax engine start.
"""

from __future__ import annotations

import asyncio
import dataclasses
import queue as _queue
import time
import zlib
from collections import deque
from typing import AsyncIterator, Callable, Dict, List, Optional

import numpy as np

from ..obs.ledger import (CLASS_DELIVERED, CLASS_DRAFT_REJECTED,
                          CLASS_HEDGE_LOSER, CLASS_PREEMPTED,
                          CLASS_QUARANTINE_BURN, CLASS_REPLAYED,
                          CLASS_WASTED_MASKED, GoodputLedger)
from ..obs.slo import (SLO_QUEUE_WAIT, SLO_SESSION_TTFT, SLO_TTFT,
                       SloEngine)
from ..obs.steptime import (PHASE_DECODE, PHASE_PREFILL,
                            PHASE_SPEC_VERIFY, StepTimeSentinel,
                            prefill_bucket)
from ..obs.trace import current_trace
from .containment import (CAUSE_SCHEDULER_DEATH, CAUSE_SCHEDULER_ERROR,
                          CAUSE_SLOT_HEALTH, PROBATION_CLEAN_CHUNKS,
                          REASON_HEALTH, REASON_ISOLATED, EngineSupervisor)
from .fallback import extract_query, rule_command  # rules promoted there
from .kv_pool import (BlockPool, HostBlockStore, PoolExhausted,
                      alloc_with_evict, map_prefix, pages_for)
from .radix_cache import RadixCache
from .protocol import (HEALTH_GRAMMAR_DEAD, HEALTH_NONFINITE,
                       EngineOverloaded, EngineResult, EngineUnavailable,
                       GenerationTimeout, RequestExport,
                       RequestQuarantined, consume_chunk_row, pack_chunk,
                       scan_chunk_row, unpack_chunk)
from .qos import (ANON_TENANT, LANE_BACKGROUND, LANE_BATCH, LANE_INTERACTIVE,
                  LANES, BrownoutController, QoSQueue, SessionBudgets,
                  current_qos, lane_rank)


class FakeEngine:
    """Deterministic pattern-matching engine.

    Test hooks:
    - ``scripted``: queue of exact responses returned before rule matching
      (use to inject unsafe output, fences, etc.)
    - ``delay``: per-call artificial latency (exercises the 504 path)
    - ``fail_with``: exception raised on next generate (exercises 500/503)
    """

    name = "fake"
    #: rule-table "weights" never change — one constant version keeps
    #: /health and X-Model-Version uniform across engine kinds.
    weights_version = "fake-rules-0"

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.scripted: List[str] = []
        self.fail_with: Optional[BaseException] = None
        self.calls = 0
        self._ready = False

    @property
    def ready(self) -> bool:
        return self._ready

    async def start(self) -> None:
        self._ready = True

    async def stop(self, drain_secs: float = 0.0) -> None:
        self._ready = False

    def _answer(self, prompt: str) -> str:
        return rule_command(extract_query(prompt))

    async def generate(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> EngineResult:
        if not self._ready:
            raise EngineUnavailable("FakeEngine not started")
        self.calls += 1
        if self.fail_with is not None:
            exc, self.fail_with = self.fail_with, None
            raise exc
        if self.delay:
            if timeout is not None and self.delay >= timeout:
                await asyncio.sleep(timeout)
                raise GenerationTimeout(f"generation exceeded {timeout}s")
            await asyncio.sleep(self.delay)
        text = self.scripted.pop(0) if self.scripted else self._answer(prompt)
        n_completion = max(len(text.split()), 1)
        return EngineResult(
            text=text,
            prompt_tokens=len(prompt.split()),
            completion_tokens=n_completion,
            decode_ms=self.delay * 1000.0,
            ttft_ms=self.delay * 1000.0,
            engine=self.name,
        )

    async def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[str]:
        result = await self.generate(
            prompt, max_tokens=max_tokens, temperature=temperature, timeout=timeout
        )
        for i, word in enumerate(result.text.split(" ")):
            yield word if i == 0 else " " + word


# ---------------------------------------------------------------------------
# FakeChunkedEngine — the decode-pipeline fake
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _FakeReq:
    prompt: str
    max_tokens: int
    deadline: Optional[float]
    out_queue: asyncio.Queue
    cancel: asyncio.Event
    stream: List[int]             # scripted token ids (ends in EOS)
    seed: int = 0                 # per-request sampling seed (recorded for
                                  # replay parity with the real contract)
    suspect_count: int = 0        # quarantine implications (containment)
    suspect: bool = False         # in the standing bisection pool
    resume_ids: Optional[List[int]] = None   # fleet migration import
    export: Optional[RequestExport] = None   # live generated-ids view
    # QoS ring (ISSUE 7) — mirror of the batcher's _Request fields so
    # the fair-share queue, preemption, and brownout are testable on
    # the fake in milliseconds.
    tenant: str = ANON_TENANT
    lane: str = LANE_INTERACTIVE
    t_submit: float = 0.0
    t_enqueue: float = 0.0
    preempt_count: int = 0
    preempt_t0: Optional[float] = None
    # True once the resume prefix's text has reached the client (set by
    # preemption — the fake's pieces are always fully emitted, so
    # suppression is whole-prefix; fleet migrations leave it False and
    # the relay suppresses by length instead).
    resume_emitted: bool = False
    # Request-lifecycle trace (obs/trace.py), captured from the
    # submitting coroutine's context — the fake runs on the event loop,
    # so the same contextvar leg the batcher's async side uses works
    # directly. Lets preempt/resume span links land on the stitched
    # /debug/requests timeline in fake-engine tests too.
    trace: Optional[object] = None
    # Goodput ledger + SLO (ISSUE 8) — mirrors of the batcher's fields:
    # tokens already billed delivered (fleet imports start at the prefix
    # the donor billed), why the next resume re-splice exists ("preempt"
    # bills preempted, else replayed), the first-token stamp that
    # survives preempt/resume, and the fleet-import TTFT exemption.
    ledger_delivered: int = 0
    resume_cause: str = ""
    t_first0: Optional[float] = None
    ttft_exempt: bool = False
    # Block-paged KV pool mirror (ISSUE 10): the prompt's token ids in
    # the fake's word-token encoding — the radix-chain key. Completion
    # pieces render as "t<id>" words, which encode back to the SAME ids,
    # so a re-sent multi-turn history radix-matches exactly like real
    # tokenization does.
    prompt_ids: List[int] = dataclasses.field(default_factory=list)
    # Grammar-constrained decoding mirror (ISSUE 11): the resolved
    # grammar profile id (-1 = unconstrained).
    gpid: int = -1
    # Session plane (ISSUE 20): the namespaced session id (empty =
    # sessionless) and whether admission radix-matched at least one full
    # page — the gate on the turn-N TTFT SLO (only returning warm turns
    # price the two-tier cache).
    session: str = ""
    radix_warm: bool = False


@dataclasses.dataclass
class _FakeSlot:
    req: _FakeReq
    emitted: List[int]            # host-consumed completion tokens
    dev_idx: int                  # device cursor into the stream
    dev_ngen: int                 # device cumulative completion count
    dev_active: bool              # device-resident live mask entry
    last_tok: int                 # device carry token (garbage repeats)
    decode_chunks_inflight: int = 0
    t_first: Optional[float] = None   # first token emitted (TTFT SLO)
    # KV pool mirror: this slot's mapped pool blocks (page order), the
    # admitted prompt ids (radix-chain basis), and the starvation flag
    # (pool exhausted even after eviction -> finish at current length).
    blocks: List[int] = dataclasses.field(default_factory=list)
    pool_ids: List[int] = dataclasses.field(default_factory=list)
    pool_starved: bool = False
    # Grammar mirror (ISSUE 11): ``gs`` = host-truth FSM state over the
    # CONSUMED stream, ``dev_gs`` = the device twin's speculative state
    # (advanced at dispatch, exactly like dev_idx/dev_ngen), and the
    # count of in-flight chunks a forced-run splice superseded.
    gs: int = 0
    dev_gs: int = 0
    stale_chunks: int = 0


class FakeChunkedEngine:
    """Numpy twin of ``BatchedJaxEngine``'s packed-chunk pipeline.

    The "device" is a scripted next-token stream per request (derived
    deterministically from the prompt unless ``stream_fn`` overrides it);
    dispatching a chunk advances device-side state speculatively exactly
    like the donated jax buffers do, packs the result through
    ``protocol.pack_chunk``, and the consume path runs the SAME
    ``consume_chunk_row`` / ``scan_chunk_row`` the real scheduler runs —
    identical termination semantics by construction, which is what makes
    the depth-sweep and done-mask parity suites meaningful.
    """

    name = "fake-chunked"

    def __init__(self, *, batch_size: int = 4, chunk_len: int = 4,
                 chunk_pipe_depth: int = 3, eos_ids=(2,),
                 device_termination: bool = True,
                 slot_health_check: bool = True,
                 quarantine_retry_budget: int = 1,
                 reset_max_per_min: int = 60,
                 max_queue_depth: int = 0,
                 tenant_max_queue: int = 0,
                 lane_weights: Optional[Dict[str, int]] = None,
                 preempt_wait_ms: float = 0.0,
                 preempt_budget: int = 2,
                 slo_interactive_ms: float = 0.0,
                 ledger_enable: bool = True,
                 slo_ttft_ms: float = 0.0,
                 slo_windows: tuple = (300, 3600),
                 slo_objective: float = 0.99,
                 kv_pool: bool = True,
                 kv_pool_page: int = 16,
                 kv_pool_blocks: int = 0,
                 radix_cache: bool = True,
                 radix_lru_blocks: int = 0,
                 host_kv_blocks: int = 0,
                 slo_session_ttft_ms: float = 0.0,
                 session_token_budget: int = 0,
                 ragged_attention: str = "auto",
                 grammar_decode: bool = False,
                 grammar_profile: str = "default",
                 grammar_forced_run_min: int = 4,
                 spec_decode: bool = False,
                 spec_draft_k: int = 4,
                 spec_fake_miss: int = 3,
                 sentinel_enable: bool = True,
                 sentinel_window: int = 256,
                 sentinel_factor: float = 2.0,
                 sentinel_min_samples: int = 16,
                 perf_baselines=None,
                 max_seq_len: int = 256,
                 faults=None,
                 weights_version: str = "fake-0",
                 stream_fn: Optional[Callable[[str], List[int]]] = None):
        if chunk_pipe_depth < 1:
            raise ValueError("chunk_pipe_depth must be >= 1")
        # Weight rollout (ISSUE 13): the fake's "weights" are the
        # keystream its scripted tokens derive from — _default_stream
        # folds the version in (the default keeps historical streams
        # byte-identical), so a version swap genuinely changes outputs
        # while two same-version replicas stay byte-identical, exactly
        # the property the fleet's version-pinned failover rests on.
        self.weights_version = str(weights_version)
        # A restorable "checkpoint" from the first breath: a rollback
        # must have something to swap back TO even for an engine that
        # never loaded from disk (swap_weights honours the version
        # override, so restoring this sentinel restores version and
        # therefore the exact byte streams).
        self.checkpoint_path: Optional[str] = (
            f"fake:initial:{self.weights_version}")
        self.batch_size = batch_size
        self.chunk_len = chunk_len
        self.chunk_pipe_depth = chunk_pipe_depth
        self.eos_ids = tuple(eos_ids)
        self.device_termination = device_termination
        self.stream_fn = stream_fn or self._default_stream
        self._ready = False
        self._slots: List[Optional[_FakeSlot]] = [None] * batch_size
        self._inflight: List[tuple] = []   # ("chunk", packed, snapshot)
        # QoS ring (ISSUE 7) — same fair-share queue + brownout +
        # preemption policy objects the batcher runs, over the fake's
        # numpy state, so the fairness/preemption matrix is testable in
        # milliseconds. Defaults (unbounded queue, preemption off) keep
        # pre-QoS tests byte-identical.
        self.max_queue_depth = max(0, max_queue_depth)
        self.preempt_wait_ms = max(0.0, preempt_wait_ms)
        self.preempt_budget = max(0, preempt_budget)
        self._brownout = BrownoutController(slo_interactive_ms)
        # Telemetry plane (ISSUE 8) — same goodput ledger + SLO burn
        # engine the batcher runs, over the fake's numpy state, so the
        # conservation invariant is assertable in milliseconds.
        self.ledger = GoodputLedger(enabled=ledger_enable)
        self._slo = SloEngine(
            {SLO_TTFT: slo_ttft_ms, SLO_QUEUE_WAIT: slo_interactive_ms,
             SLO_SESSION_TTFT: slo_session_ttft_ms},
            objective=slo_objective, windows=tuple(slo_windows))
        # Per-session token budgets (ISSUE 20): charged at delivery,
        # read at classification — both engines share the policy object
        # type so budget semantics can't diverge.
        self._session_budgets = SessionBudgets(session_token_budget)
        # Perf-regression sentinel (ISSUE 15) — the SAME StepTimeSentinel
        # the batcher runs, fed by the same dispatch-interval scheme, so
        # the whole sentinel → trigger → incident chain runs in tier-1:
        # a chunk-path delay fault stretches dispatch intervals exactly
        # like a slow device. The fake's μs-scale steps mean only the
        # self-calibrated envelope is meaningful here; decode samples
        # key by the batch rung (the fake has no KV bucket ladder).
        self._steptime = StepTimeSentinel(
            enabled=sentinel_enable, window=sentinel_window,
            factor=sentinel_factor, min_samples=sentinel_min_samples,
            baselines=perf_baselines)
        self._steptime_pending = None
        self._steptime_consumed = False
        self._preemptions = 0
        self._preempted_tokens = 0
        self._preempt_times: deque = deque(maxlen=512)
        self._preempt_for_lane: Optional[str] = None
        self._queue: QoSQueue = QoSQueue(
            max_depth=self.max_queue_depth,
            tenant_cap=max(0, tenant_max_queue),
            weights=lane_weights,
            on_expire=self._expire_queued)
        self._task: Optional[asyncio.Task] = None
        self._monitor: Optional[asyncio.Task] = None
        #: testing/faults.py injector (decode / scheduler points).
        self.faults = faults
        # Fault containment (ISSUE 5) — the numpy twin of the batcher's
        # inner ring: same supervisor policy object, same health lane in
        # the packed buffer, same quarantine/bisect/reset-replay flow,
        # so the recovery matrix is testable in milliseconds.
        self.slot_health_check = slot_health_check
        self.supervisor = EngineSupervisor(
            retry_budget=quarantine_retry_budget,
            max_resets_per_min=reset_max_per_min)
        self._parked: List[_FakeSlot] = []
        self._probation_clean = 0  # clean chunks consumed this probation
        # Mirrors of the batcher's pipeline counters (stats() parity).
        self._wasted_steps = 0
        self._fetches = 0
        self._chunks_dispatched = 0
        self._chunks_consumed = 0
        self._chunks_pruned = 0
        self._last_n_alive = 0
        # Chunk-event ring (mirror of the batcher's): /debug/chunks and
        # the incident bundles read it, so the evidence chain runs in
        # tier-1 on the fake too.
        self._chunk_log: deque = deque(maxlen=512)
        # Block-paged KV pool mirror (ISSUE 10): the SAME BlockPool /
        # RadixCache objects and the SAME kv_pool.map_prefix admission
        # path the batcher runs — the fake's KV is fictional (scripted
        # streams), but every alloc/incref/decref/COW/insert/evict is
        # real, so the leak and sharing invariants run in tier-1 on CPU
        # against production refcount code.
        self.kv_pool = bool(kv_pool)
        self.kv_pool_page = max(1, kv_pool_page)
        self.radix_cache = bool(radix_cache)
        self.radix_lru_blocks = max(0, radix_lru_blocks)
        self.max_seq_len = max(chunk_len + 1, max_seq_len)
        self._pool_max_pages = pages_for(self.max_seq_len + chunk_len,
                                         self.kv_pool_page)
        self._pool_n_blocks = (max(0, kv_pool_blocks)
                               or batch_size * self._pool_max_pages)
        # Two-tier KV (ISSUE 20): host-RAM capacity behind the radix
        # tree; 0 keeps the single-tier world byte-identical.
        self.host_kv_blocks = max(0, host_kv_blocks)
        self._pool: Optional[BlockPool] = None
        self._radix: Optional[RadixCache] = None
        self._host_store: Optional[HostBlockStore] = None
        self._pool_starved = 0
        if self.kv_pool:
            self._pool_reset()
        # Ragged paged attention mirror (ISSUE 19): the fake has no
        # kernels, so this mirrors the SCHEDULER policy only — "on"
        # defers the admission's first sampled token to the next chunk
        # (the batcher's staged-admission prologue), so the deferral
        # bookkeeping (TTFT catch at consume, budget/EOS-at-first edges,
        # grammar first-pick in-chunk) runs in tier-1. "auto" resolves
        # off here — the real auto gate is TPU-only.
        if ragged_attention not in ("auto", "on", "off"):
            raise ValueError(
                f"RAGGED_ATTENTION must be auto|on|off, "
                f"got {ragged_attention!r}")
        self.ragged_attention = ragged_attention
        self._use_ragged = (ragged_attention == "on" and self.kv_pool
                            and self.device_termination)
        self._attention_regime = ("ragged" if self._use_ragged
                                  else "paged" if self.kv_pool
                                  else "dense")
        # Admission width of staged (deferred-first-token) admissions
        # since the last dispatch — keys that dispatch's sentinel
        # sample as a ragged prefill phase (mirror of the batcher).
        self._pending_adm_w = 0
        # Grammar-constrained decoding mirror (ISSUE 11): the SAME
        # GrammarRuntime/TokenFSM compile the batcher runs, built
        # against the ByteTokenizer the fake's grammar streams use
        # (token ids 3..258 = UTF-8 bytes), stepped host-side per
        # scripted token — the tier-1 home of the grammar invariants
        # (never an off-grammar token, dead ends trip the health lane,
        # forced splices keep the pool books balanced).
        if grammar_decode and not device_termination:
            raise ValueError("GRAMMAR_DECODE requires DEVICE_TERMINATION")
        self.grammar_decode = bool(grammar_decode)
        self.grammar_forced_run_min = max(1, grammar_forced_run_min)
        self._grammar = None
        if self.grammar_decode:
            from ..constrain import GrammarRuntime
            from .tokenizer import ByteTokenizer

            tok = ByteTokenizer()
            self._grammar = GrammarRuntime(
                tok, tok.vocab_size, self.eos_ids,
                profile=grammar_profile,
                forced_run_min=self.grammar_forced_run_min)
        self._grammar_forced = 0
        self._grammar_masked = 0
        self._grammar_dead_ends: Dict[str, int] = {}
        self._grammar_ff_splices = 0
        # Speculative decoding mirror (ISSUE 12): the fake's "draft
        # model" is a deterministic oracle that predicts the scripted
        # stream's next token except at miss indices
        # (``spec_fake_miss`` = every ~Nth draft is wrong; 0 = a
        # perfect draft) — so the accept/reject machinery, the packed
        # v3 lanes, the draft_rejected billing, and the draft:die
        # degradation all run in tier-1 with a dialable acceptance
        # rate, while spec on/off byte-identity stays structural (the
        # emitted tokens are the scripted stream either way, which is
        # exactly the real engine's exact-match-verification
        # guarantee).
        if spec_decode and not device_termination:
            raise ValueError("SPEC_DECODE requires DEVICE_TERMINATION")
        if spec_decode and spec_draft_k < 1:
            raise ValueError(
                f"SPEC_DRAFT_K must be >= 1, got {spec_draft_k}")
        self.spec_decode = bool(spec_decode)
        self.spec_draft_k = int(spec_draft_k)
        self.spec_fake_miss = max(0, int(spec_fake_miss))
        self._use_spec = self.spec_decode
        self._spec_live = self.spec_decode
        self._spec_steps = (max(1, chunk_len // (spec_draft_k + 1))
                            if self.spec_decode else 0)
        self._chunk_tokens = (self._spec_steps * (spec_draft_k + 1)
                              if self.spec_decode else chunk_len)
        self._spec_drafted = 0
        self._spec_accepted = 0
        self._spec_degraded = 0
        # ISSUE 18 surface parity: the fake has no mesh, so its draft
        # world is never sharded and never in the gather fallback.
        self._draft_sharded = False
        self._draft_kv_fallback = False

    # ----------------------------------- speculative decoding (mirror)

    def _spec_active(self) -> bool:
        return self._use_spec and self._spec_live

    def _chunk_waste_bound(self) -> int:
        """Mirror of the batcher's: per-in-flight-chunk bound on counted
        steps for the preempt/disconnect waste caps (spec chunks are
        ``_chunk_tokens`` wide, possibly > chunk_len)."""
        if self._use_spec:
            return max(self.chunk_len, self._chunk_tokens)
        return self.chunk_len

    def _spec_miss(self, req: _FakeReq, idx: int) -> bool:
        """Deterministic draft-miss oracle: does the fake's draft model
        mispredict the scripted stream at index ``idx``? Keyed on
        (seed, idx) so replays/preemptions reproduce the same
        acceptance pattern the original run had."""
        if self.spec_fake_miss <= 0:
            return False
        return (idx * 2654435761 + req.seed) % self.spec_fake_miss == 0

    def spec_health(self) -> Optional[dict]:
        """Cheap speculative-decode view for /health (mirror of the
        batcher's)."""
        if not self.spec_decode:
            return None
        drafted = self._spec_drafted
        return {
            "enabled": self.spec_decode,
            "active": self._spec_active(),
            "draft_model": "fake-draft",
            "k": self.spec_draft_k,
            "verify_steps_per_chunk": self._spec_steps,
            "drafted_tokens_total": drafted,
            "accepted_tokens_total": self._spec_accepted,
            "acceptance_ratio": (round(self._spec_accepted / drafted, 4)
                                 if drafted else None),
            "degraded_total": self._spec_degraded,
            "draft_sharded": self._draft_sharded,
            "draft_kv_fallback": self._draft_kv_fallback,
        }

    # ------------------------------------- block-paged KV pool (mirror)

    def _pool_reset(self) -> None:
        """(Re-)build the allocator world — the fake analog of the
        batcher's pool rebuild on a containment reset: every cached
        block's (fictional) KV is invalid, so ownership restarts empty
        and replays re-allocate. Cumulative counters carry over (the
        /metrics delta-mirror must never see totals go backwards)."""
        prev_pool, prev_radix = self._pool, self._radix
        prev_store = self._host_store
        self._pool = BlockPool(self._pool_n_blocks, self.kv_pool_page)
        # Two-tier rebuild (ISSUE 20): a containment reset condemns the
        # host tier too — its payloads were captured from the poisoned
        # device world — so BOTH tiers restart empty; cumulative demote/
        # onload counters carry like the pool's.
        self._host_store = (HostBlockStore(self.host_kv_blocks)
                            if self.host_kv_blocks > 0 and self.radix_cache
                            else None)
        self._radix = (RadixCache(self._pool,
                                  max_blocks=self.radix_lru_blocks,
                                  host_store=self._host_store,
                                  faults=self.faults)
                       if self.radix_cache else None)
        if prev_pool is not None:
            self._pool.carry_counters(prev_pool)
        if prev_radix is not None and self._radix is not None:
            self._radix.carry_counters(prev_radix)
        if prev_store is not None and self._host_store is not None:
            self._host_store.carry_counters(prev_store)

    @staticmethod
    def _prompt_token_ids(prompt: str) -> List[int]:
        """Word-token encoding with the completion round-trip property:
        the fake's completion pieces are "t<id>" words, which encode
        back to exactly ``id`` — so a multi-turn prompt that re-sends
        prompt + completion text extends the cached chain's ids
        verbatim, and the radix tree matches the whole history (the
        real tokenizer gives the batcher the same property)."""
        out = []
        for w in prompt.split():
            if len(w) > 1 and w[0] == "t" and w[1:].isdigit():
                out.append(int(w[1:]))
            else:
                out.append(
                    1_000_000
                    + zlib.crc32(w.encode("utf-8", "surrogatepass"))
                    % 1_000_000)
        return out

    def _pool_map_prefix(self, ids: List[int], match_all: bool = False):
        """kv_pool.map_prefix — the batcher's exact admission path; the
        COW callback is None because only the accounting is real here
        (the copy itself is device work)."""
        return map_prefix(self._pool, self._radix, ids,
                          match_all=match_all, cow=None)

    def _pool_seat(self, req: _FakeReq, g: int) -> tuple:
        """Allocate one seating's chain: the replay basis is
        prompt + emitted[:-1] (the rows a real device has verifiably
        written). Returns (blocks, pool_ids); raises PoolExhausted with
        refs released."""
        if self._pool is None:
            return [], []
        basis = list(req.prompt_ids)
        gen = list(req.resume_ids or [])[:g]
        chain = basis + (gen[:-1] if gen else [])
        blocks, m = self._pool_map_prefix(chain, match_all=bool(gen))
        # Session SLO gate (ISSUE 20): a seating that radix-matched at
        # least one full page is a warm re-admission — the only kind the
        # turn-N TTFT SLO judges (onload-served pages count here too:
        # map_prefix's match promoted them before recording the hit).
        req.radix_warm = m >= self.kv_pool_page
        return blocks, basis

    def _pool_ensure_coverage(self, slot: _FakeSlot,
                              chunk_tokens: Optional[int] = None) -> bool:
        """Grow the slot's chain to cover the next chunk's writes
        (mirror of the batcher's dispatch-time growth; starvation
        truncates the request at its current length, never corrupts).
        ``chunk_tokens`` is the dispatching chunk's own token capacity
        (wider under speculative decode)."""
        if self._pool is None or slot.pool_starved:
            return not slot.pool_starved
        target = min(len(slot.pool_ids) + slot.dev_ngen
                     + (chunk_tokens or self.chunk_len),
                     len(slot.pool_ids) + slot.req.max_tokens)
        need = pages_for(target, self.kv_pool_page)
        while len(slot.blocks) < need:
            b = alloc_with_evict(self._pool, self._radix, 1)
            if b is None:
                slot.pool_starved = True
                self._pool_starved += 1
                return False
            slot.blocks.extend(b)
            if slot.req.export is not None:
                slot.req.export.blocks = list(slot.blocks)
        return True

    def _pool_release_slot(self, slot: _FakeSlot,
                           cache_chain: bool = True) -> None:
        """Mirror of the batcher's release: clean finishes insert the
        verified chain (prompt + emitted[:-1]) into the radix tree
        first — completion feeds sharing — then the slot's refs drop
        (shared blocks decay to cached, private ones free)."""
        if self._pool is None or not slot.blocks:
            slot.blocks = []
            return
        if cache_chain and self._radix is not None and slot.pool_ids:
            chain = slot.pool_ids + (slot.emitted[:-1] if slot.emitted
                                     else [])
            chain = chain[:len(slot.blocks) * self.kv_pool_page]
            try:
                self._radix.insert(chain, slot.blocks)
            except Exception:  # pragma: no cover - defensive
                pass
        self._pool.decref(slot.blocks)
        slot.blocks = []

    def kv_pool_health(self) -> Optional[dict]:
        """Cheap pool view for /health (mirror of the batcher's)."""
        if self._pool is None:
            return None
        cached = (self._radix.cached_blocks() if self._radix is not None
                  else ())
        body = self._pool.stats(cached).as_dict()
        body["starved_slots_total"] = self._pool_starved
        body["radix"] = (self._radix.stats() if self._radix is not None
                         else None)
        if self._host_store is not None:
            body["host_tier"] = self._host_store.stats()
        # ISSUE 19 surface parity: the regime actually serving decode
        # attention (policy mirror — the fake has no kernels).
        body["attention_regime"] = self._attention_regime
        return body

    # ------------------------------- grammar-constrained decode (ISSUE 11)

    def _grammar_pick(self, gs: int, raw: int) -> Optional[int]:
        """The fake's 'renormalized draw': the scripted token when it is
        grammar-legal from ``gs``, else the deterministic fallback —
        lowest legal non-EOS token (EOS only when it is the sole legal
        move). None = dead end (no legal token at all); the caller
        freezes the slot on HEALTH_GRAMMAR_DEAD exactly like the jitted
        scan."""
        allowed = self._grammar.allowed_np(gs)
        if 0 <= raw < allowed.shape[0] and allowed[raw]:
            return raw
        legal = np.nonzero(allowed)[0]
        if legal.size == 0:
            return None
        non_eos = [int(t) for t in legal if int(t) not in self.eos_ids]
        return non_eos[0] if non_eos else int(legal[0])

    def _grammar_note_dead_end(self, cause: str) -> None:
        self._grammar_dead_ends[cause] = \
            self._grammar_dead_ends.get(cause, 0) + 1

    def _grammar_consume(self, slot: _FakeSlot, new_ids) -> None:
        for t in new_ids:
            slot.gs = self._grammar.advance(slot.gs, int(t))
        self._grammar_masked += len(new_ids)

    def _grammar_fast_forward(self, idx: int, slot: _FakeSlot) -> None:
        """Forced-run fast-forward, numpy twin of the batcher's: splice
        the single-successor chain in one step, mark the superseded
        in-flight chunks stale, re-derive the device cursors at the
        post-run indices (the scripted stream's entries for those
        indices were going to be coerced to exactly these tokens — the
        same singleton-support argument that makes the real splice
        byte-identical to masked step-by-step decode)."""
        if (self._grammar is None or slot.req.gpid < 0
                or slot.pool_starved):
            return
        req = slot.req
        g = len(slot.emitted)
        cap = req.max_tokens - g
        if cap <= 0:
            return
        run, ends_eos, end_gs = self._grammar.forced_run(slot.gs, cap)
        covered = slot.decode_chunks_inflight * (
            self._chunk_tokens if self._spec_active() else self.chunk_len)
        net = len(run) - covered
        if net < self.grammar_forced_run_min and not (
                ends_eos and run and net > 0):
            return
        slot.emitted.extend(run)
        slot.gs = end_gs
        slot.dev_gs = end_gs
        slot.dev_idx = len(slot.emitted)
        slot.dev_ngen = len(slot.emitted)
        slot.last_tok = run[-1]
        if req.export is not None:
            req.export.ids = list(slot.emitted)
        self._grammar_forced += len(run)
        self._grammar_ff_splices += 1
        if slot.decode_chunks_inflight > 0:
            self._bill_waste(min(covered, cap), req)
            slot.stale_chunks += slot.decode_chunks_inflight
        if self._pool is not None:
            self._pool_ensure_coverage(slot)
        req.out_queue.put_nowait(
            ("token", self._piece(run, g)))
        if req.trace is not None:
            req.trace.event(
                f"grammar: forced run of {len(run)} tokens spliced")
        if len(slot.emitted) >= req.max_tokens:
            self._finish(idx, "length")
            return
        if ends_eos:
            self._finish(idx, "stop")
            return
        slot.dev_active = True

    def grammar_health(self) -> Optional[dict]:
        if self._grammar is None:
            return None
        body = dict(self._grammar.health())
        body["forced_tokens_total"] = self._grammar_forced
        body["masked_steps_total"] = self._grammar_masked
        body["fast_forward_splices_total"] = self._grammar_ff_splices
        body["dead_ends_total"] = dict(self._grammar_dead_ends)
        return body

    # ----------------------------------------------------------- streams

    def _default_stream(self, prompt: str) -> List[int]:
        """Deterministic ragged stream: 3-25 tokens drawn from a crc32
        keystream (values kept clear of the EOS ids), EOS-terminated.
        The keystream is keyed on (weights version, prompt) — swapped
        "weights" really do change the transcript — with the default
        version keeping the historical prompt-only keying so every
        pre-rollout byte expectation holds verbatim."""
        key = (prompt if self.weights_version == "fake-0"
               else f"{self.weights_version}|{prompt}")
        h = zlib.crc32(key.encode())
        n = 3 + h % 23
        lo = max(self.eos_ids) + 1
        return [lo + ((h >> (i % 24)) + 7 * i) % 211
                for i in range(n)] + [self.eos_ids[0]]

    def _stream_at(self, stream: List[int], idx: int) -> int:
        """Past-the-end reads repeat EOS — the 'garbage' a real model
        decodes after termination collapses to EOS here, which the legacy
        host scan treats exactly like the jax engine treats its garbage
        (discarded after the terminating token)."""
        return stream[idx] if idx < len(stream) else self.eos_ids[0]

    # ---------------------------------------------------------- lifecycle

    @property
    def ready(self) -> bool:
        return self._ready

    async def start(self) -> None:
        self._ready = True
        self._task = asyncio.create_task(self._loop())
        self._monitor = asyncio.create_task(self._supervise())

    async def stop(self, drain_secs: float = 0.0) -> None:
        if drain_secs > 0:
            deadline = time.monotonic() + drain_secs
            self._ready = False     # no new admissions
            while time.monotonic() < deadline:
                if not (self._queue or self._inflight or self._parked
                        or any(self._slots)):
                    break
                await asyncio.sleep(0.01)
        self._ready = False
        for task_attr in ("_task", "_monitor"):
            task = getattr(self, task_attr)
            if task is not None:
                task.cancel()
                try:
                    await task
                except BaseException:
                    # CancelledError normally; a SchedulerKilled drill
                    # corpse surfaces here too — both are expected.
                    pass
                setattr(self, task_attr, None)
        for slot in self._slots:
            if slot is not None:
                self._pool_release_slot(slot, cache_chain=False)
                slot.req.out_queue.put_nowait(
                    ("error", EngineUnavailable("engine stopped")))
        self._slots = [None] * self.batch_size
        for slot in self._parked:
            slot.req.out_queue.put_nowait(
                ("error", EngineUnavailable("engine stopped")))
        self._parked.clear()
        for req in self._queue.drain():
            req.out_queue.put_nowait(
                ("error", EngineUnavailable("engine stopped")))
        self._inflight.clear()

    def swap_weights(self, path: str, *, version: Optional[str] = None
                     ) -> str:
        """Weight-swap mirror (ISSUE 13) of the batcher's: requires a
        stopped (drained) engine, is atomic under the
        ``checkpoint:corrupt`` drill (the prior version stays armed),
        dies attributably under ``swap:fail``, and rebuilds the KV-pool
        world exactly like a containment reset — so the rollout state
        machine, version-pinned failover, and rollback books are all
        testable in tier-1 milliseconds."""
        from .rollout import (CheckpointCorrupt, RolloutError, SwapFailed,
                              checkpoint_version)

        if self._ready:
            raise RolloutError(
                "swap_weights requires a stopped (drained) engine")
        version = version or checkpoint_version(path)
        if self.faults is not None \
                and hasattr(self.faults, "checkpoint_corrupt") \
                and self.faults.checkpoint_corrupt():
            raise CheckpointCorrupt(
                f"checkpoint {path!r} failed integrity validation "
                f"(injected checkpoint:corrupt drill)")
        if self.faults is not None \
                and hasattr(self.faults, "swap_fail") \
                and self.faults.swap_fail():
            # Mid-swap death: the old "weights" are gone — serving this
            # replica again without a successful re-swap would serve
            # unknown bytes, so it stays down (cause swap_failed) and
            # both stamps clear together (batcher mirror).
            self.weights_version = ""
            self.checkpoint_path = None
            raise SwapFailed(
                "injected swap:fail — replica died mid-swap")
        self.weights_version = version
        self.checkpoint_path = str(path)
        if self._pool is not None:
            # New weights invalidate every cached block's (fictional)
            # KV — the ownership world restarts empty, like a reset.
            self._pool_reset()
        return version

    def set_reset_listener(self, fn) -> None:
        """Wire engine resets to the service layer (the PR 1 breaker) —
        same hook the batcher exposes."""
        self.supervisor.on_reset = fn

    def stats(self) -> dict:
        return {
            "batch_occupancy": sum(s is not None for s in self._slots),
            "queue_depth": self._queue.qsize(),
            "qos": dict(self._queue.stats(),
                        lane_occupancy=self.lane_occupancy(),
                        preemptions=self._preemptions,
                        preempted_tokens=self._preempted_tokens,
                        brownout_level=self._brownout.level,
                        brownout_transitions=self._brownout.transitions,
                        lane_shares={
                            k: round(v, 4)
                            for k, v in self._brownout.shares.items()}),
            "pipe_depth": self.chunk_pipe_depth,
            "pipe_inflight": len(self._inflight),
            "device_active_slots": self._last_n_alive,
            "device_termination": self.device_termination,
            "wasted_decode_steps": self._wasted_steps,
            "chunks_dispatched": self._chunks_dispatched,
            "chunks_consumed": self._chunks_consumed,
            "chunks_pruned": self._chunks_pruned,
            "fetches": self._fetches,
            "containment": dict(self.supervisor.stats(),
                                parked=len(self._parked),
                                slot_health_check=self.slot_health_check),
            "kv_pool": self.kv_pool_health(),
            "ledger": self.ledger.snapshot(),
            "slo": self._slo.snapshot(),
            "grammar": self.grammar_health(),
            "spec": self.spec_health(),
            "steptime": self._steptime.snapshot(),
        }

    def steptime_health(self) -> dict:
        """Cheap step-time sentinel view (mirror of the batcher's)."""
        return self._steptime.snapshot()

    # ------------------------------------------ telemetry plane (ISSUE 8)

    def _bill_waste(self, n: int, req: Optional[_FakeReq]) -> None:
        """Mirror of the batcher's: one call site bills the legacy
        wasted-steps counter AND the ledger's wasted_masked class."""
        if n <= 0:
            return
        self._wasted_steps += n
        lane = getattr(req, "lane", LANE_INTERACTIVE) if req is not None \
            else LANE_INTERACTIVE
        tenant = getattr(req, "tenant", None) if req is not None else None
        self.ledger.record(CLASS_WASTED_MASKED, n, lane=lane, tenant=tenant)

    def slo_health(self) -> dict:
        return self._slo.snapshot()

    def ledger_snapshot(self) -> dict:
        snap = self.ledger.snapshot()
        snap["tenants"] = self.ledger.tenant_snapshot()
        snap["conservation"] = self.ledger.conservation()
        return snap

    # ---------------------------------------------------------- scheduler

    async def _loop(self) -> None:
        while True:
            try:
                progressed = self._tick()
            except Exception as e:
                # A poisoned step, not a dead engine: quarantine/bisect +
                # reset-and-replay, exactly like the batcher's widened
                # scheduler except. SchedulerKilled (a BaseException)
                # deliberately escapes — the task dies and _supervise
                # restarts it.
                self._contain_poisoned_step(CAUSE_SCHEDULER_ERROR, error=e)
                progressed = True
            await asyncio.sleep(0 if progressed else 0.001)

    async def _supervise(self) -> None:
        """Scheduler-death recovery (the async twin of the batcher's
        _supervise_scheduler thread): when the loop task dies of an
        uncatchable fault, reset, replay survivors, restart the loop —
        queued requests sit untouched in self._queue throughout."""
        while True:
            await asyncio.sleep(0.005)
            task = self._task
            if task is None or not task.done() or not self._ready:
                continue
            task.exception()   # retrieve (the corpse is expected)
            survivors = [s for s in self._slots if s is not None]
            self._slots = [None] * self.batch_size
            self._inflight.clear()
            if self._pool is not None:
                self._pool_reset()
                for s in survivors + self._parked:
                    s.blocks = []
                    s.pool_starved = False
            if not self.supervisor.allow_reset():
                self._ready = False
                err = EngineUnavailable(
                    "scheduler dead; engine reset budget exhausted")
                for slot in survivors + self._parked:
                    slot.req.out_queue.put_nowait(("error", err))
                self._parked.clear()
                for req in self._queue.drain():
                    req.out_queue.put_nowait(("error", err))
                return
            self.supervisor.note_reset(CAUSE_SCHEDULER_DEATH)
            for slot in survivors:
                self._replay_slot(slot)
            self._task = asyncio.create_task(self._loop())

    def _tick(self) -> bool:
        if self.faults is not None:
            self.faults.check_scheduler_die()
        self._sweep()
        if (self._parked and not self._inflight
                and all(s is None for s in self._slots)):
            # Probe group drained clean: unpark the held half (they
            # resume from their generated-so-far prefixes). Long probes
            # are exonerated earlier, in _consume_oldest.
            self._unpark_parked()
            return True
        # QoS ring: brownout evaluation + preemptive decode (mirror of
        # the batcher's worker-loop placement — the freed slot is handed
        # to the starved lane by the _admit_pending call right below).
        self._brownout.maybe_eval(
            burn_fn=lambda: self._slo.fast_burn(
                SLO_QUEUE_WAIT, LANE_INTERACTIVE))
        self._maybe_preempt()
        self._admit_pending()
        self._prune_dead_chunks()
        n_active = sum(s is not None for s in self._slots)
        if n_active and len(self._inflight) < self.chunk_pipe_depth:
            self._dispatch_chunk()
            return True
        if self._inflight:
            self._consume_oldest()
            return True
        return False

    def _sweep(self) -> None:
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if slot.req.cancel.is_set():
                self._finish(i, "abort", wasted_inflight=True)
            elif (slot.req.deadline is not None
                  and time.monotonic() > slot.req.deadline):
                self._finish(i, "timeout",
                             error=GenerationTimeout("generation timeout"),
                             wasted_inflight=True)
            elif slot.pool_starved and slot.decode_chunks_inflight == 0:
                self._finish(i, "length")

    # --------------------------------------------- QoS ring (ISSUE 7)

    def lane_occupancy(self) -> Dict[str, int]:
        """Slots held per lane (mirror of the batcher's — the fleet's
        lane-aware router reads this)."""
        counts = {lane: 0 for lane in LANES}
        for s in self._slots:
            if s is not None:
                lane = getattr(s.req, "lane", LANE_INTERACTIVE)
                counts[lane if lane in LANES else LANE_INTERACTIVE] += 1
        return counts

    def _capped_lanes(self, counts: Dict[str, int]) -> tuple:
        capped = []
        for lane in (LANE_BACKGROUND, LANE_BATCH):
            cap = self._brownout.lane_cap(lane, self.batch_size)
            if cap < self.batch_size and counts.get(lane, 0) >= cap:
                capped.append(lane)
        return tuple(capped)

    def _expire_queued(self, req: _FakeReq) -> None:
        req.out_queue.put_nowait(
            ("error", GenerationTimeout("deadline expired while queued")))

    def _credit_preempt_wait(self, req: _FakeReq) -> None:
        t0 = req.preempt_t0
        if t0 is None:
            return
        req.preempt_t0 = None
        if req.deadline is not None:
            req.deadline += time.monotonic() - t0

    def _maybe_preempt(self) -> bool:
        """Mirror of the batcher's preemptive decode over the fake's
        scripted streams: export the cheapest lower-lane victim, free
        its slot for the starved lane, replay bit-identically later
        (the scripted stream IS the seeded-sampling determinism)."""
        if self.preempt_wait_ms <= 0 or self._parked:
            return False
        if any(s is None for s in self._slots):
            return False
        now = time.monotonic()
        lane = self._queue.starved_lane(
            now, self.preempt_wait_ms / 1000.0,
            exclude=self._capped_lanes(self.lane_occupancy()))
        if lane is None:
            return False
        rank = lane_rank(lane)
        victims = [
            (i, s) for i, s in enumerate(self._slots)
            if s is not None
            and lane_rank(getattr(s.req, "lane", LANE_INTERACTIVE)) < rank
            and s.req.preempt_count < self.preempt_budget
        ]
        if not victims:
            return False
        idx, _ = min(victims, key=lambda t: (lane_rank(t[1].req.lane),
                                             len(t[1].emitted)))
        self._preempt_slot(idx, lane)
        self._preempt_for_lane = lane
        return True

    def _preempt_slot(self, idx: int, for_lane: str) -> None:
        slot = self._slots[idx]
        self._slots[idx] = None
        req = slot.req
        req.preempt_count += 1
        req.preempt_t0 = time.monotonic()
        req.resume_ids = list(slot.emitted)
        req.resume_emitted = True    # fake pieces are always fully emitted
        # Mirror the batcher: no cause marker when nothing was generated
        # (the fresh re-admission path never consumes it).
        req.resume_cause = "preempt" if slot.emitted else ""
        if req.export is not None:
            req.export.ids = list(slot.emitted)
        if self.device_termination and slot.decode_chunks_inflight > 0:
            remaining = max(0, req.max_tokens - len(slot.emitted))
            self._bill_waste(min(
                slot.decode_chunks_inflight * self._chunk_waste_bound(),
                remaining), req)
        self._preemptions += 1
        self._preempted_tokens += len(slot.emitted)
        self._preempt_times.append(req.preempt_t0)
        if req.trace is not None:
            req.trace.link("preempted", from_slot=idx,
                           tokens=len(slot.emitted), for_lane=for_lane,
                           lane=req.lane)
        # Pool mirror: cache the victim's chain so its resume re-maps
        # shared blocks instead of re-prefilling.
        self._pool_release_slot(slot, cache_chain=True)
        self._queue.requeue_head(req)

    def _inject_flood(self, n: int) -> None:
        """tenant:flood:<n> drill — synthetic background-tenant burst
        (mirror of the batcher's)."""
        from ..testing.faults import FLOOD_LANE, FLOOD_TENANT

        now = time.monotonic()
        for i in range(n):
            prompt = f"tenant flood drill {i}"
            req = _FakeReq(
                prompt=prompt,
                prompt_ids=self._prompt_token_ids(prompt),
                max_tokens=32,
                deadline=now + 30.0,
                out_queue=asyncio.Queue(),
                cancel=asyncio.Event(),
                stream=list(self.stream_fn(prompt)),
                seed=i,
                tenant=FLOOD_TENANT,
                lane=FLOOD_LANE,
                t_submit=now,
            )
            try:
                self._queue.put(req)
            except EngineOverloaded:
                break

    def qos_health(self) -> dict:
        now = time.monotonic()
        return {
            "lanes": self._queue.lane_depths(),
            "brownout_level": self._brownout.level,
            "lane_shares": {k: round(v, 4)
                            for k, v in self._brownout.shares.items()},
            "preemptions_total": self._preemptions,
            "preemptions_last_60s": sum(
                1 for t in list(self._preempt_times) if t >= now - 60.0),
            "queue_expired_total": self._queue.expired_total,
            "queue_displaced_total": self._queue.displaced_total,
            "session_budgets": self._session_budgets.snapshot(),
        }

    def _admit_pending(self) -> None:
        if self._parked:
            # Bisection probation (mirror of the batcher): no new
            # admissions may join a suspect batch; queued requests wait
            # and are never dropped.
            return
        counts = self.lane_occupancy()
        prefer, self._preempt_for_lane = self._preempt_for_lane, None
        while None in self._slots:
            try:
                req = self._queue.get_nowait(
                    exclude_lanes=self._capped_lanes(counts),
                    min_lane=prefer)
            except _queue.Empty:
                if prefer is None:
                    break
                prefer = None
                continue
            prefer = None
            if req.cancel.is_set():
                continue
            self._credit_preempt_wait(req)
            t_adm0 = time.monotonic()
            lane = req.lane if req.lane in LANES else LANE_INTERACTIVE
            counts[lane] += 1
            if req.t_submit:
                wait_ms = (time.monotonic() - req.t_submit) * 1000.0
                self._brownout.note_queue_wait(lane, wait_ms)
                # Mirror the batcher: resumes (preemption returns, fleet
                # imports) are NOT fresh queue waits — their wall since
                # t_submit includes time spent decoding.
                if not req.resume_ids:
                    self._slo.note(SLO_QUEUE_WAIT, lane, wait_ms)
            i = self._slots.index(None)
            if req.resume_ids:
                # Cross-replica import (fleet migration) or preemption
                # resume: re-seat from the portable generated prefix —
                # device cursors resume at g. The prefix TEXT is
                # re-emitted only for migrations (the fleet relay
                # suppresses it); a preempted victim's client already
                # has it (resume_emitted). Pool mirror: the replay basis
                # (prompt + prefix[:-1]) radix-matches the chain the
                # preemption cached, so a resume re-MAPS shared blocks
                # instead of re-prefilling (kv_pool.map_prefix).
                g = len(req.resume_ids)
                try:
                    blocks, basis = self._pool_seat(req, g)
                except PoolExhausted:
                    req.out_queue.put_nowait(("error", EngineUnavailable(
                        "admission failed: kv pool exhausted")))
                    continue
                gs_r = 0
                if self._grammar is not None and req.gpid >= 0:
                    # Re-derive the FSM state from the imported prefix
                    # (mirror of the batcher's replay re-arm).
                    gs_r = self._grammar.run(req.gpid, req.resume_ids)
                slot = _FakeSlot(
                    req=req, emitted=list(req.resume_ids), dev_idx=g,
                    dev_ngen=g,
                    dev_active=(g < req.max_tokens
                                if self.device_termination else True),
                    last_tok=req.resume_ids[-1],
                    t_first=time.monotonic(),
                    blocks=blocks, pool_ids=basis,
                    gs=gs_r, dev_gs=gs_r)
                if req.export is not None and blocks:
                    req.export.blocks = list(blocks)
                if not req.resume_emitted:
                    req.out_queue.put_nowait(
                        ("token", self._piece(slot.emitted, 0)))
                req.resume_emitted = True
                if req.export is not None:
                    req.export.ids = list(slot.emitted)
                # Ledger: the resume re-derives g tokens (mirror of the
                # batcher's _replay_slot billing — preemption resumes
                # bill preempted, migration imports bill replayed). A
                # budget-spent import never re-splices, so it bills
                # nothing — same as the batcher's early finish.
                cls = (CLASS_PREEMPTED if req.resume_cause == "preempt"
                       else CLASS_REPLAYED)
                req.resume_cause = ""
                if g < req.max_tokens:
                    self.ledger.record(cls, g, lane=lane,
                                       tenant=req.tenant)
                    if req.trace is not None:
                        req.trace.link("resumed", slot=i, tokens=g)
                self._slots[i] = slot
                if g >= req.max_tokens:
                    self._finish(i, "length")
                continue
            # Admission "prefill": the stream's first token is emitted
            # immediately (the batcher pipelines it as a "first" entry;
            # collapsing that here keeps the fake synchronous without
            # changing chunk semantics). Grammar mirror: the first
            # token is the masked pick from the START state — or, when
            # the START state's forced chain clears the net-win bar
            # (it always does on a fresh slot: nothing is in flight),
            # the whole run splices at admission exactly like the
            # batcher rides it on the prompt prefill.
            grammar_on = self._grammar is not None and req.gpid >= 0
            run: List[int] = []
            ends_eos = False
            gs0 = -1
            if grammar_on:
                gs0 = self._grammar.start_state(req.gpid)
                run, ends_eos, gs_end = self._grammar.forced_run(
                    gs0, req.max_tokens)
                if len(run) >= self.grammar_forced_run_min or (
                        ends_eos and run):
                    gs0 = gs_end
                else:
                    run, ends_eos = [], False
            if run:
                emitted0 = list(run)
            elif self._use_ragged:
                # Ragged admission mirror (ISSUE 19): the first SAMPLED
                # token is NOT picked here — the next chunk's first row
                # emits stream[0] (through the same in-chunk grammar
                # pick / EOS / budget folds every decode step runs), so
                # the slot seats with an empty transcript and TTFT rides
                # the consume path's first-token catch.
                emitted0 = []
            else:
                first = req.stream[0]
                if grammar_on:
                    picked = self._grammar_pick(gs0, first)
                    if picked is None:   # structurally unreachable
                        self._grammar_note_dead_end("admission")
                        req.out_queue.put_nowait(
                            ("error", EngineUnavailable(
                                "grammar dead end at admission")))
                        continue
                    first = picked
                if first in self.eos_ids:
                    req.out_queue.put_nowait(
                        ("done", self._result(req, [], "stop")))
                    continue
                emitted0 = [first]
                if grammar_on:
                    gs0 = self._grammar.advance(gs0, first)
                    self._grammar_masked += 1
            try:
                blocks, basis = self._pool_seat(req, 0)
            except PoolExhausted:
                req.out_queue.put_nowait(("error", EngineUnavailable(
                    "admission failed: kv pool exhausted")))
                continue
            slot = _FakeSlot(req=req, emitted=emitted0,
                             dev_idx=len(emitted0),
                             dev_ngen=len(emitted0),
                             dev_active=req.max_tokens > len(emitted0),
                             last_tok=emitted0[-1] if emitted0 else 0,
                             t_first=(time.monotonic() if emitted0
                                      else None),
                             blocks=blocks, pool_ids=basis,
                             gs=gs0, dev_gs=gs0)
            if req.export is not None and blocks:
                req.export.blocks = list(blocks)
            if req.t_first0 is None:
                req.t_first0 = slot.t_first
            if not self.device_termination:
                slot.dev_active = True
            self._slots[i] = slot
            if self._use_ragged:
                # Ragged admission: the prefill "program" rides the next
                # chunk — that dispatch's sentinel sample is a PREFILL
                # phase keyed by the admission width, not a decode
                # sample (mirror of the batcher's mixed-chunk keying).
                self._pending_adm_w = max(
                    self._pending_adm_w,
                    prefill_bucket(len(req.prompt_ids)))
            else:
                # Sentinel prefill sample (mirror of the batcher's
                # admission→first-token measurement; the fake's
                # "prefill" is host work, μs-scale — the self-calibrated
                # envelope makes it a meaningful regression signal
                # regardless).
                self._steptime.note(
                    PHASE_PREFILL, prefill_bucket(len(req.prompt_ids)),
                    time.monotonic() - t_adm0,
                    tokens=len(req.prompt_ids))
            if req.export is not None:
                req.export.ids = list(slot.emitted)
            if emitted0:
                req.out_queue.put_nowait(
                    ("token", self._piece(emitted0, 0)))
            if run:
                self._grammar_forced += len(run)
                self._grammar_ff_splices += 1
                if self._pool is not None:
                    self._pool_ensure_coverage(slot)
            if len(slot.emitted) >= req.max_tokens:
                self._finish(i, "length")
            elif run and ends_eos:
                self._finish(i, "stop")

    def _dispatch_chunk(self) -> None:
        """The 'device': advance every live slot's stream cursor by up to
        chunk_len steps, folding EOS/budget termination into the live
        mask exactly like the jitted scan does, and pack one buffer.
        decode:nan corruption mirrors the jitted detection: the corrupt
        slot's health bit sets, its row repeats the carry token, and
        (device termination) it freezes before counting anything.

        Speculative decode (ISSUE 12): a spec chunk runs
        ``_spec_steps`` draft/verify windows instead — each window
        emits 1..k+1 tokens depending on where the deterministic
        draft-miss oracle first disagrees with the scripted stream —
        and packs the wider row plus the v3 drafted/accepted lanes.
        The EMITTED tokens are the scripted stream either way (the
        exact-match-verification guarantee), so spec on/off transcripts
        are byte-identical by construction here too."""
        if self.faults is not None:
            # Chunk-path fault seam (mirror of the batcher's): a delay/
            # hang here stalls the dispatch loop exactly like a slow
            # device dispatch — the step-time sentinel drill's
            # injection point.
            self.faults.check("chunk")
        if (self._spec_active() and self.faults is not None
                and self.faults.draft_die()):
            # draft:die — the draft engine is gone; degrade to plain
            # decode mid-stream without failing anything (mirror of
            # the batcher).
            self._spec_live = False
            self._spec_degraded += 1
        spec = self._spec_active() and self.device_termination
        # Step-time sentinel sample (mirror of the batcher's gating): a
        # dispatch interval counts only when a consume happened since
        # the previous dispatch AND the pipe never emptied.
        now = time.monotonic()
        pend = self._steptime_pending
        if pend is not None and self._steptime_consumed and self._inflight:
            t0, phase0, bucket0, (steps0, toks0) = pend
            self._steptime.note(phase0, bucket0, now - t0,
                                steps=steps0, tokens=toks0, now=now)
        n_live = sum(s is not None for s in self._slots)
        ct0 = self._chunk_tokens if spec else self.chunk_len
        # Ragged admission (ISSUE 19): a chunk carrying a staged
        # admission is a PREFILL-phase sample keyed by the admission
        # width, so mixed chunks never pollute the decode digests
        # (mirror of the batcher's keying).
        adm_w, self._pending_adm_w = self._pending_adm_w, 0
        self._steptime_pending = (
            now,
            PHASE_PREFILL if adm_w else (
                PHASE_SPEC_VERIFY if spec else PHASE_DECODE),
            adm_w if adm_w else self.batch_size, (ct0, ct0 * n_live))
        self._steptime_consumed = False
        N = self.batch_size
        C = self._chunk_tokens if spec else self.chunk_len
        toks = np.zeros((N, C), np.int32)
        done = np.zeros((N,), bool)
        lengths = np.zeros((N,), np.int32)
        health = np.zeros((N,), np.int32)
        drafted = np.zeros((N,), np.int32)
        accepted = np.zeros((N,), np.int32)
        corrupt: set = set()
        if self.faults is not None:
            corrupt = set(self.faults.decode_nan_slots(
                [s.req.prompt if s is not None else None
                 for s in self._slots]))
        snapshot: List[Optional[_FakeReq]] = [None] * N
        for i, slot in enumerate(self._slots):
            if slot is None:
                continue
            if (self._pool is not None
                    and not self._pool_ensure_coverage(slot, C)):
                # Pool starved even after radix eviction: the slot is
                # excluded from this chunk and finishes at its current
                # length once its in-flight chunks drain (mirror of the
                # batcher's exhausted-slot handling).
                continue
            snapshot[i] = slot.req
            slot.decode_chunks_inflight += 1
            live = slot.dev_active
            if i in corrupt and self.slot_health_check and (
                    live or not self.device_termination):
                health[i] = HEALTH_NONFINITE
                if self.device_termination:
                    # Frozen at detection: carry token repeats, nothing
                    # is counted — live_lengths stay at the pre-chunk
                    # value, like the jitted scan's in-chunk freeze.
                    toks[i, :] = slot.last_tok
                    done[i] = True
                    slot.dev_active = False
                    lengths[i] = slot.dev_ngen
                    continue
            grammar_on = (self._grammar is not None
                          and slot.req.gpid >= 0)
            if spec:
                self._spec_slot_rows(i, slot, toks, done, lengths,
                                     health, drafted, accepted,
                                     grammar_on, live)
                continue
            for step in range(C):
                if self.device_termination:
                    if not live:
                        toks[i, step] = slot.last_tok
                        continue
                    nxt = self._stream_at(slot.req.stream, slot.dev_idx)
                    if grammar_on:
                        # Grammar mirror: the scripted token passes
                        # only if legal from the device FSM state; an
                        # illegal one renormalizes to the deterministic
                        # fallback; NO legal token = dead end — freeze
                        # on the grammar health bit exactly like the
                        # jitted scan (nothing from this state is ever
                        # emitted).
                        picked = self._grammar_pick(slot.dev_gs, nxt)
                        if picked is None:
                            health[i] |= HEALTH_GRAMMAR_DEAD
                            toks[i, step:] = slot.last_tok
                            live = False
                            break
                        nxt = picked
                    toks[i, step] = nxt
                    slot.last_tok = nxt
                    if nxt in self.eos_ids:
                        live = False
                        continue
                    if grammar_on:
                        slot.dev_gs = self._grammar.advance(
                            slot.dev_gs, nxt)
                    slot.dev_idx += 1
                    slot.dev_ngen += 1
                    if slot.dev_ngen >= slot.req.max_tokens:
                        live = False
                else:
                    # Legacy: the device decodes the full chunk blind.
                    nxt = self._stream_at(slot.req.stream, slot.dev_idx)
                    toks[i, step] = nxt
                    slot.last_tok = nxt
                    slot.dev_idx += 1
                    slot.dev_ngen += 1
            if self.device_termination:
                done[i] = not live
                slot.dev_active = live
            lengths[i] = slot.dev_ngen
        n_alive = sum(
            1 for s in self._slots if s is not None and s.dev_active
        ) if self.device_termination else sum(
            s is not None for s in self._slots)
        packed = pack_chunk(toks, done, lengths, n_alive, health=health,
                            drafted=drafted if spec else None,
                            accepted=accepted if spec else None)
        self._inflight.append(("chunk", packed, snapshot, C, spec))
        self._chunks_dispatched += 1
        self._chunk_log.append({
            "t": time.time(), "event": "dispatch",
            "slots": sum(s is not None for s in snapshot),
            "pipe": len(self._inflight),
        })

    def _spec_slot_rows(self, i: int, slot: _FakeSlot, toks, done,
                        lengths, health, drafted, accepted,
                        grammar_on: bool, live: bool) -> None:
        """One slot's speculative chunk: ``_spec_steps`` windows of
        (carry + k drafts), each accepting tokens until the draft-miss
        oracle first disagrees — the same per-position termination /
        grammar / EOS fold as the plain loop, writing compacted rows
        through a cursor exactly like the jitted spec scan."""
        K = self.spec_draft_k
        toks[i, :] = slot.last_tok      # garbage-by-contract fill
        cur = 0
        for _it in range(self._spec_steps):
            if not live:
                break
            drafted[i] += K
            idx0 = slot.dev_idx
            for j in range(K + 1):
                if j >= 1 and self._spec_miss(slot.req, idx0 + j - 1):
                    # Draft j-1 mispredicted: this window's later
                    # positions were conditioned on the wrong token —
                    # dead for the window, re-drafted next one.
                    break
                nxt = self._stream_at(slot.req.stream, slot.dev_idx)
                if grammar_on:
                    picked = self._grammar_pick(slot.dev_gs, nxt)
                    if picked is None:
                        health[i] |= HEALTH_GRAMMAR_DEAD
                        live = False
                        break
                    nxt = picked
                toks[i, cur] = nxt
                slot.last_tok = nxt
                if nxt in self.eos_ids:
                    live = False
                    break
                if grammar_on:
                    slot.dev_gs = self._grammar.advance(slot.dev_gs,
                                                        nxt)
                slot.dev_idx += 1
                slot.dev_ngen += 1
                cur += 1
                if j >= 1:
                    accepted[i] += 1
                if slot.dev_ngen >= slot.req.max_tokens:
                    live = False
                    break
        done[i] = not live
        slot.dev_active = live
        lengths[i] = slot.dev_ngen

    def _prune_dead_chunks(self) -> None:
        while self._inflight:
            snapshot = self._inflight[0][2]
            live = any(
                snap is not None and self._slots[i] is not None
                and self._slots[i].req is snap
                for i, snap in enumerate(snapshot)
            )
            if live:
                return
            entry = self._inflight.pop(0)
            if not self.device_termination:
                # Mirror the batcher: pruned legacy chunks executed a full
                # chunk of garbage per dispatched slot.
                for snap in entry[2]:
                    if snap is not None:
                        self._bill_waste(self.chunk_len, snap)
            self._chunks_pruned += 1

    def _consume_oldest(self) -> None:
        _, packed, snapshot, ct, is_spec = self._inflight.pop(0)
        if self.faults is not None:
            # decode:poison_step — step-wide fault from the fetch, routed
            # into the bisecting containment by the loop's except.
            self.faults.poison_fetch(
                [r.prompt if r is not None else None for r in snapshot])
        self._fetches += 1          # the single fetch per chunk
        res = unpack_chunk(packed, self.batch_size, ct, spec=is_spec)
        self._chunks_consumed += 1
        self._steptime_consumed = True   # arms the next dispatch's sample
        self._chunk_log.append({
            "t": time.time(), "event": "consume", "n_alive": res.n_alive,
            "pipe": len(self._inflight),
        })
        self._last_n_alive = res.n_alive
        # Speculative accounting (mirror of the batcher): acceptance
        # counters + the draft_rejected waste class, billed BEFORE the
        # health-trip early return so the books balance under drills.
        if is_spec and res.drafted is not None:
            for i in range(self.batch_size):
                req_i = snapshot[i]
                if req_i is None:
                    continue
                d, a = int(res.drafted[i]), int(res.accepted[i])
                if d <= 0:
                    continue
                self._spec_drafted += d
                self._spec_accepted += a
                if d > a:
                    self.ledger.record(
                        CLASS_DRAFT_REJECTED, d - a,
                        lane=getattr(req_i, "lane", LANE_INTERACTIVE),
                        tenant=req_i.tenant)
        # Slot-health quarantine: nothing from a poisoned chunk is
        # emitted; replay regenerates the innocents bit-identically.
        tripped = [
            i for i in range(self.batch_size)
            if int(res.health[i]) and snapshot[i] is not None
            and self._slots[i] is not None
            and self._slots[i].req is snapshot[i]
        ]
        if tripped:
            self.supervisor.note_health_trips(len(tripped))
            for i in tripped:
                if int(res.health[i]) & HEALTH_GRAMMAR_DEAD:
                    self._grammar_note_dead_end("decode")
            self._contain_poisoned_step(
                CAUSE_SLOT_HEALTH,
                named=[self._slots[i] for i in tripped])
            return
        for i, slot in enumerate(self._slots):
            if slot is None or slot.req is not snapshot[i]:
                if snapshot[i] is not None and not self.device_termination:
                    self._bill_waste(self.chunk_len, snapshot[i])
                continue
            slot.decode_chunks_inflight -= 1
            if slot.stale_chunks > 0:
                # Superseded by a forced-run fast-forward (its rows
                # index the pre-splice stream; FIFO consume keeps the
                # countdown exact — mirror of the batcher).
                slot.stale_chunks -= 1
                continue
            if self.device_termination:
                new_ids, finish = consume_chunk_row(
                    res.tokens[i], bool(res.done[i]), int(res.lengths[i]),
                    len(slot.emitted), ct, self.eos_ids)
            else:
                new_ids, finish, wasted = scan_chunk_row(
                    res.tokens[i], len(slot.emitted), self.eos_ids,
                    slot.req.max_tokens)
                self._bill_waste(wasted, slot.req)
            if new_ids:
                if slot.t_first is None:
                    # Ragged admission (ISSUE 19): the first sampled
                    # token rode this chunk — TTFT lands here.
                    slot.t_first = time.monotonic()
                    if slot.req.t_first0 is None:
                        slot.req.t_first0 = slot.t_first
                piece = self._piece(new_ids, len(slot.emitted))
                slot.emitted.extend(new_ids)
                if slot.req.export is not None:
                    slot.req.export.ids = list(slot.emitted)
                slot.req.out_queue.put_nowait(("token", piece))
                if self._grammar is not None and slot.req.gpid >= 0:
                    self._grammar_consume(slot, new_ids)
                    if finish is None:
                        self._grammar_fast_forward(i, slot)
                        if self._slots[i] is not slot:
                            continue
            if finish is not None:
                self._finish(i, finish)
        # Early exoneration (mirror of the batcher): after
        # PROBATION_CLEAN_CHUNKS clean chunks that actually TESTED a
        # flagged suspect, suspicion narrows to the parked half, which
        # replays now instead of stalling admissions until the probe
        # drains; with nothing parked, the cleared flags close the case.
        if any(r is not None and r.suspect for r in snapshot):
            self._probation_clean += 1
            if self._probation_clean >= PROBATION_CLEAN_CHUNKS:
                self._probation_clean = 0
                for s in self._slots:
                    if s is not None:
                        s.req.suspect = False
                if self._parked:
                    self._unpark_parked()
        elif self._parked and not any(
                s is not None and s.req.suspect for s in self._slots
        ) and not any(
                r is not None and r.suspect
                for e in self._inflight if e[0] == "chunk" for r in e[2]):
            # Every probe suspect completed and none remains in the pipe:
            # the parked half inherits the suspicion now.
            self._unpark_parked()

    # ------------------------------------------- containment (ISSUE 5)

    def _fail_all_active(self, error: BaseException) -> None:
        self._inflight.clear()
        for i, slot in enumerate(self._slots):
            if slot is not None:
                self._slots[i] = None
                self._pool_release_slot(slot, cache_chain=False)
                self._bill_delivered(slot.req, len(slot.emitted))
                slot.req.out_queue.put_nowait(("error", error))
        for slot in self._parked:
            # Parked slots' block lists were cleared at the reset that
            # parked them (stale-generation views) — release is a no-op
            # there by construction.
            self._pool_release_slot(slot, cache_chain=False)
            self._bill_delivered(slot.req, len(slot.emitted))
            slot.req.out_queue.put_nowait(("error", error))
        self._parked.clear()

    def _bill_delivered(self, req: _FakeReq, n_total: int) -> None:
        """Bill the emitted transcript as delivered, incrementally past
        what was already billed (a fleet import's prefix was billed by
        the donor — see _FakeReq.ledger_delivered). A cancelled
        hedge-loser branch (export.discard) emitted tokens the relay
        never forwarded — hedge_loser burn, not delivered (mirror of
        the batcher's _finish)."""
        n_new = n_total - req.ledger_delivered
        req.ledger_delivered = n_total
        cls = (CLASS_HEDGE_LOSER
               if (req.export is not None
                   and getattr(req.export, "discard", False))
               else CLASS_DELIVERED)
        self.ledger.record(cls, n_new, lane=req.lane, tenant=req.tenant)
        # Session budget (ISSUE 20): only tokens the client actually got
        # spend budget — hedge-loser burn never demotes a session.
        if cls == CLASS_DELIVERED:
            self._session_budgets.charge(req.session, n_new)

    def _contain_poisoned_step(self, cause: str, named=(),
                               error: Optional[BaseException] = None) -> None:
        """Quarantine + reset-and-replay — the same flow as
        BatchedJaxEngine._contain_poisoned_step over numpy state (the
        'reset' here is dropping the speculative pipeline; per-slot
        device state is re-derived from host truth by _replay_slot)."""
        survivors = [s for s in self._slots if s is not None]
        if not self.supervisor.allow_reset():
            self._fail_all_active(
                error if isinstance(error, Exception)
                else EngineUnavailable("engine reset budget exhausted"))
            return
        quarantined: List[_FakeSlot] = []
        reasons: dict = {}
        pool = list(survivors)
        if named:
            for slot in named:
                if self.supervisor.implicate(slot.req):
                    quarantined.append(slot)
                    reasons[id(slot)] = REASON_HEALTH
        else:
            # Mirror of the batcher: narrow to the standing suspect pool
            # so early exoneration can't widen the next bisection back
            # out to the whole batch.
            flagged = [s for s in survivors if s.req.suspect]
            if flagged:
                pool = flagged
            if len(pool) == 1:
                slot = pool[0]
                if self.supervisor.implicate(slot.req):
                    quarantined.append(slot)
                    reasons[id(slot)] = REASON_ISOLATED
        self._slots = [None] * self.batch_size
        self._inflight.clear()
        if self._pool is not None:
            # Mirror the batcher's reset: the pool world rebuilds empty
            # (cached KV would be device-invalid there), and survivors'
            # block lists are stale previous-generation views — cleared
            # so nothing ever decrefs stale ids into the fresh pool.
            self._pool_reset()
            for s in survivors:
                s.blocks = []
                s.pool_starved = False
        self.supervisor.note_reset(cause)
        qset = {id(s) for s in quarantined}
        for slot in quarantined:
            self.supervisor.note_quarantine(reasons[id(slot)])
            # Ledger: the quarantined transcript is discarded — burned,
            # never delivered (mirror of the batcher).
            burn = len(slot.emitted) - slot.req.ledger_delivered
            slot.req.ledger_delivered = len(slot.emitted)
            self.ledger.record(CLASS_QUARANTINE_BURN, burn,
                               lane=slot.req.lane, tenant=slot.req.tenant)
            slot.req.out_queue.put_nowait(("error", RequestQuarantined(
                f"request quarantined after poisoning {cause} "
                f"{slot.req.suspect_count}x (retry budget "
                f"{self.supervisor.retry_budget})")))
        rest = [s for s in survivors
                if id(s) not in qset and not s.req.cancel.is_set()]
        if named:
            probe, parked = rest, []
        else:
            # Bisect within the suspect pool only; non-suspects replay
            # immediately alongside the probe (mirror of the batcher).
            pool_rest = [s for s in pool
                         if id(s) not in qset and not s.req.cancel.is_set()]
            pool_ids = {id(s) for s in pool_rest}
            innocents = [s for s in rest if id(s) not in pool_ids]
            if len(pool_rest) <= 1:
                probe, parked = rest, []
            else:
                probe_sus, parked = EngineSupervisor.split(pool_rest)
                probe = probe_sus + innocents
            for s in innocents:
                s.req.suspect = False
            for s in pool_rest:
                s.req.suspect = True
        self._parked.extend(parked)
        self._probation_clean = 0   # each containment pass restarts probation
        for slot in probe:
            self._replay_slot(slot)

    def _unpark_parked(self) -> None:
        """End bisection probation: replay every parked slot and let
        admissions resume on the next tick."""
        parked, self._parked = self._parked, []
        self._probation_clean = 0
        for slot in parked:
            self._replay_slot(slot)

    def _replay_slot(self, slot: _FakeSlot) -> None:
        """Re-seat one surviving request: the device cursors re-derive
        from the host-side emitted prefix (the scripted stream is the
        'model', so replayed tokens are bit-identical by construction —
        exactly the property the jax engine gets from seeded sampling)."""
        req = slot.req
        if req.cancel.is_set():
            return
        g = len(slot.emitted)
        i = self._slots.index(None)
        if self._pool is not None:
            # Pool mirror of the batcher's replay: re-derive the chain
            # through the radix tree (a preempt-cached or shared prefix
            # re-maps; after a reset the empty tree means fresh blocks).
            chain = slot.pool_ids + (slot.emitted[:-1] if slot.emitted
                                     else [])
            try:
                slot.blocks, _ = self._pool_map_prefix(chain,
                                                       match_all=True)
            except PoolExhausted:
                req.out_queue.put_nowait(("error", EngineUnavailable(
                    "replay failed: kv pool exhausted")))
                return
            if req.export is not None and slot.blocks:
                req.export.blocks = list(slot.blocks)
        slot.dev_idx = g
        slot.dev_ngen = g
        slot.last_tok = slot.emitted[-1] if slot.emitted else 0
        slot.dev_active = (g < req.max_tokens
                           if self.device_termination else True)
        slot.decode_chunks_inflight = 0
        slot.stale_chunks = 0
        if self._grammar is not None and req.gpid >= 0:
            slot.gs = self._grammar.run(req.gpid, slot.emitted)
            slot.dev_gs = slot.gs
        self._slots[i] = slot
        self.supervisor.note_replay(g)
        # Ledger: the containment replay re-derives the emitted prefix
        # (the fake's cursors jump, but accounting mirrors the real
        # engine's re-splice prefill).
        self.ledger.record(CLASS_REPLAYED, g, lane=req.lane,
                           tenant=req.tenant)
        if req.trace is not None:
            req.trace.link("resumed", slot=i, tokens=g)

    def _finish(self, slot_idx: int, finish: str,
                error: Optional[BaseException] = None,
                wasted_inflight: bool = False) -> None:
        slot = self._slots[slot_idx]
        self._slots[slot_idx] = None
        if slot is None:  # pragma: no cover - defensive
            return
        # Pool mirror: release blocks; clean finishes cache the chain
        # first (completion feeds sharing — same rule as the batcher).
        self._pool_release_slot(
            slot, cache_chain=(error is None
                               and finish in ("stop", "length")))
        # Mirror the batcher's billing: capped by the remaining token
        # budget — the device freezes there, so a disconnect near natural
        # completion can't read as a full pipe of waste.
        if (wasted_inflight and self.device_termination
                and slot.decode_chunks_inflight > 0):
            remaining = max(0, slot.req.max_tokens - len(slot.emitted))
            self._bill_waste(min(
                slot.decode_chunks_inflight * self._chunk_waste_bound(),
                remaining), slot.req)
        # Ledger + TTFT SLO (mirror of the batcher's _finish).
        self._bill_delivered(slot.req, len(slot.emitted))
        if error is not None:
            slot.req.out_queue.put_nowait(("error", error))
            return
        now = time.monotonic()
        if (slot.req.t_submit and not slot.req.ttft_exempt
                and not (slot.req.export is not None
                         and getattr(slot.req.export, "discard", False))):
            # t_first0 survives preempt/resume (mirror of the batcher);
            # fleet imports are exempt — their first byte was the
            # donor's.
            ttft_ms = ((slot.req.t_first0 or slot.t_first or now)
                       - slot.req.t_submit) * 1000.0
            lane = (slot.req.lane if slot.req.lane in LANES
                    else LANE_INTERACTIVE)
            self._slo.note(SLO_TTFT, lane, ttft_ms, now=now)
            # Turn-N session TTFT (ISSUE 20): judged ONLY for radix-warm
            # re-admissions of a declared session — the sample set the
            # two-tier cache is accountable for.
            if slot.req.session and slot.req.radix_warm:
                self._slo.note(SLO_SESSION_TTFT, lane, ttft_ms, now=now)
        # Starvation truncation is a client-visible degradation (ISSUE
        # 20): the transcript is short of what decode would have
        # produced, so the result says so instead of passing as a
        # natural stop.
        degraded = bool(slot.pool_starved)
        if degraded and slot.req.trace is not None:
            slot.req.trace.link("degraded", cause="kv_pool_starved",
                                tokens=len(slot.emitted))
        # Stamped AFTER construction: _result is a documented test
        # override hook, so its signature stays what subclasses expect.
        result = self._result(slot.req, slot.emitted, finish)
        result.degraded = result.degraded or degraded
        slot.req.out_queue.put_nowait(("done", result))

    # ------------------------------------------------------------ serving

    def _piece(self, ids: List[int], offset: int) -> str:
        """Token ids → text increment. Default rendering is "t<id>"
        words (the round-trip encoding the radix suites rely on); under
        GRAMMAR_DECODE the tokens ARE ByteTokenizer byte ids, so pieces
        render as the real UTF-8 text — the HTTP end-to-end grammar
        tests read actual kubectl commands off the wire."""
        if self._grammar is not None:
            return self._grammar.tokenizer.decode(ids)
        text = " ".join(f"t{t}" for t in ids)
        return text if offset == 0 else " " + text

    def _result(self, req: _FakeReq, ids: List[int],
                finish: str) -> EngineResult:
        return EngineResult(
            text=(self._grammar.tokenizer.decode(ids)
                  if self._grammar is not None
                  else " ".join(f"t{t}" for t in ids)),
            prompt_tokens=len(req.prompt.split()),
            completion_tokens=len(ids),
            finish_reason=finish,
            engine=self.name,
            weights_version=self.weights_version,
        )

    async def stream_events(self, prompt: str, *, max_tokens: int = 128,
                            temperature: float = 0.0,
                            timeout: Optional[float] = None,
                            seed: Optional[int] = None,
                            resume_ids: Optional[List[int]] = None,
                            export: Optional[RequestExport] = None):
        """Fleet-facing event stream — the same cross-replica contract
        the batcher speaks (seed pin, resume import, live export);
        ``temperature`` is accepted for signature parity and ignored
        (streams are scripted)."""
        del temperature
        async for ev in self._stream_events(
                prompt, max_tokens=max_tokens, timeout=timeout, seed=seed,
                resume_ids=resume_ids, export=export):
            yield ev

    async def _stream_events(self, prompt: str, *, max_tokens: int,
                             timeout: Optional[float],
                             seed: Optional[int] = None,
                             resume_ids: Optional[List[int]] = None,
                             export: Optional[RequestExport] = None):
        if not self._ready:
            raise EngineUnavailable("FakeChunkedEngine not started")
        if seed is None:
            seed = zlib.crc32(
                prompt.encode("utf-8", "surrogatepass")) & 0x7FFFFFFF
        # QoS classification + fair-share admission (mirror of the
        # batcher's submit path).
        qctx = current_qos()
        tenant = (qctx.tenant if qctx is not None else "") or ANON_TENANT
        lane = (qctx.lane if qctx is not None
                and qctx.lane in LANES else LANE_INTERACTIVE)
        session = qctx.session if qctx is not None else ""
        # Over-budget sessions classify into the background lane (ISSUE
        # 20): the session keeps working — WDRR guarantees background a
        # share — but stops outranking fresh interactive traffic.
        lane = self._session_budgets.lane_for(session, lane)
        gpid = -1
        if self._grammar is not None:
            from ..constrain import current_grammar

            gctx = current_grammar()
            if gctx is not None and gctx.allowed_verbs:
                # Mirror the batcher: a novel verb set compiles a
                # variant FSM — keep that off the event loop.
                gpid = await asyncio.to_thread(
                    self._grammar.resolve, lane=lane, ctx=gctx)
            else:
                gpid = self._grammar.resolve(lane=lane, ctx=gctx)
        if self.faults is not None:
            burst = self.faults.tenant_flood()
            if burst:
                self._inject_flood(burst)
        now = time.monotonic()
        req = _FakeReq(
            prompt=prompt,
            prompt_ids=self._prompt_token_ids(prompt),
            max_tokens=max(1, max_tokens),
            deadline=(now + timeout) if timeout else None,
            out_queue=asyncio.Queue(),
            cancel=asyncio.Event(),
            stream=list(self.stream_fn(prompt)),
            seed=int(seed),
            resume_ids=list(resume_ids) if resume_ids else None,
            export=export,
            tenant=tenant,
            lane=lane,
            t_submit=now,
            trace=current_trace(),
            # Fleet import: the prefix was decoded and billed delivered
            # on the donor replica (see _FakeReq.ledger_delivered), and
            # the client's first byte happened there too.
            ledger_delivered=len(resume_ids) if resume_ids else 0,
            ttft_exempt=bool(resume_ids),
            gpid=gpid,
            session=session,
        )
        if export is not None:
            # Version the portable state at submit (ISSUE 13): the
            # fleet's version-pinned failover routes on this stamp.
            export.weights_version = self.weights_version
        # put() raises TenantOverloaded (429) at the per-tenant cap and
        # EngineOverloaded when this tenant floods a full queue; a quiet
        # arrival instead displaces the flooder's newest request.
        for victim in self._queue.put(req):
            victim.out_queue.put_nowait(("error", EngineOverloaded(
                f"displaced from a full admission queue (tenant "
                f"{victim.tenant!r} holds the largest queue share)")))
        try:
            while True:
                if req.deadline is not None:
                    remaining = req.deadline - time.monotonic()
                    try:
                        event, payload = await asyncio.wait_for(
                            req.out_queue.get(), remaining + 2.0)
                    except asyncio.TimeoutError:
                        raise GenerationTimeout(
                            "generation exceeded timeout")
                else:
                    event, payload = await req.out_queue.get()
                if event == "error":
                    raise payload
                yield (event, payload)
                if event == "done":
                    return
        finally:
            req.cancel.set()

    async def generate(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
        seed: Optional[int] = None,
    ) -> EngineResult:
        async for event, payload in self._stream_events(
                prompt, max_tokens=max_tokens, timeout=timeout, seed=seed):
            if event == "done":
                return payload
        raise EngineUnavailable("stream ended without a result")

    async def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
        seed: Optional[int] = None,
    ) -> AsyncIterator[str]:
        async for event, payload in self._stream_events(
                prompt, max_tokens=max_tokens, timeout=timeout, seed=seed):
            if event == "token":
                yield payload
