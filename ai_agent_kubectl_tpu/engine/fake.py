"""FakeEngine — deterministic engine for tests (SURVEY.md §4, boundary 1).

Maps a handful of natural-language patterns to canned kubectl commands and
supports scripted responses/latency/failures so API tests can exercise every
status code without a TPU or network.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Dict, List, Optional

from .fallback import extract_query, rule_command  # rules promoted there
from .protocol import EngineResult, EngineUnavailable, GenerationTimeout


class FakeEngine:
    """Deterministic pattern-matching engine.

    Test hooks:
    - ``scripted``: queue of exact responses returned before rule matching
      (use to inject unsafe output, fences, etc.)
    - ``delay``: per-call artificial latency (exercises the 504 path)
    - ``fail_with``: exception raised on next generate (exercises 500/503)
    """

    name = "fake"

    def __init__(self, delay: float = 0.0):
        self.delay = delay
        self.scripted: List[str] = []
        self.fail_with: Optional[BaseException] = None
        self.calls = 0
        self._ready = False

    @property
    def ready(self) -> bool:
        return self._ready

    async def start(self) -> None:
        self._ready = True

    async def stop(self, drain_secs: float = 0.0) -> None:
        self._ready = False

    def _answer(self, prompt: str) -> str:
        return rule_command(extract_query(prompt))

    async def generate(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> EngineResult:
        if not self._ready:
            raise EngineUnavailable("FakeEngine not started")
        self.calls += 1
        if self.fail_with is not None:
            exc, self.fail_with = self.fail_with, None
            raise exc
        if self.delay:
            if timeout is not None and self.delay >= timeout:
                await asyncio.sleep(timeout)
                raise GenerationTimeout(f"generation exceeded {timeout}s")
            await asyncio.sleep(self.delay)
        text = self.scripted.pop(0) if self.scripted else self._answer(prompt)
        n_completion = max(len(text.split()), 1)
        return EngineResult(
            text=text,
            prompt_tokens=len(prompt.split()),
            completion_tokens=n_completion,
            decode_ms=self.delay * 1000.0,
            ttft_ms=self.delay * 1000.0,
            engine=self.name,
        )

    async def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[str]:
        result = await self.generate(
            prompt, max_tokens=max_tokens, temperature=temperature, timeout=timeout
        )
        for i, word in enumerate(result.text.split(" ")):
            yield word if i == 0 else " " + word
