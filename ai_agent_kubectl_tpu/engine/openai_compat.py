"""OpenAI-compatible remote engine (reference parity path).

Re-implements the reference's LangChain ``ChatOpenAI`` call
(app.py:106-122, 183-186) as a direct httpx ChatCompletions client, for
BASELINE config 1 and for pointing at any local OpenAI-compatible stub
server (the reference's ``OPENAI_BASE_URL`` escape hatch, app.py:114-115).

temperature=0 default matches app.py:109.
"""

from __future__ import annotations

import asyncio
import json
import time
import weakref
from typing import AsyncIterator, Optional

import httpx

from .protocol import EngineResult, EngineUnavailable, GenerationTimeout


class OpenAICompatEngine:
    name = "openai"

    def __init__(
        self,
        api_key: Optional[str],
        model: str = "gpt-3.5-turbo",
        base_url: Optional[str] = None,
        timeout: float = 60.0,
    ):
        self.api_key = api_key
        self.model = model
        self.base_url = (base_url or "https://api.openai.com/v1").rstrip("/")
        self.timeout = timeout
        self._client: Optional[httpx.AsyncClient] = None
        self._inflight = 0
        self._draining = False
        self._stop_now = False      # force-stop: ends an in-progress drain

    @property
    def ready(self) -> bool:
        return (self._client is not None and bool(self.api_key)
                and not self._draining)

    async def start(self) -> None:
        self._draining = False
        headers = {}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        self._client = httpx.AsyncClient(
            base_url=self.base_url, headers=headers, timeout=self.timeout
        )

    async def stop(self, drain_secs: float = 0.0) -> None:
        # Drain: stop accepting (ready drops), wait for in-flight proxied
        # requests before closing the shared httpx client under them.
        if self._draining and drain_secs <= 0:
            # Force path (second signal): make the in-progress drain below
            # finish promptly and let IT own the single client close —
            # closing here would yank the shared client out from under the
            # very streams the drain exists to protect (code review r5).
            self._stop_now = True
            return
        self._draining = True
        self._stop_now = False
        if drain_secs > 0:
            deadline = time.monotonic() + drain_secs
            while (self._inflight > 0 and not self._stop_now
                   and time.monotonic() < deadline):
                await asyncio.sleep(0.05)
        if self._client is not None:
            await self._client.aclose()
            self._client = None

    async def generate(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> EngineResult:
        if self._client is None or not self.api_key or self._draining:
            raise EngineUnavailable("OpenAI engine not initialized (missing key?)"
                                    if not self._draining else
                                    "engine draining")
        t0 = time.monotonic()
        self._inflight += 1
        try:
            resp = await self._client.post(
                "/chat/completions",
                json={
                    "model": self.model,
                    "messages": [{"role": "user", "content": prompt}],
                    "temperature": temperature,
                    "max_tokens": max_tokens,
                },
                timeout=timeout or self.timeout,
            )
        except httpx.TimeoutException as e:
            raise GenerationTimeout(str(e)) from e
        except httpx.HTTPError as e:
            # Connect/read/protocol failures map to the same degraded-mode
            # exception as initialization failures (reference 503 path).
            raise EngineUnavailable(f"upstream request failed: {e}") from e
        finally:
            self._inflight -= 1
        if resp.status_code >= 400:
            # Same mapping as the streaming path: upstream HTTP errors are
            # engine unavailability, not an internal 500.
            raise EngineUnavailable(
                f"upstream returned {resp.status_code}: {resp.text[:200]}"
            )
        data = resp.json()
        text = data["choices"][0]["message"]["content"]
        usage = data.get("usage", {})
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        return EngineResult(
            text=text,
            prompt_tokens=usage.get("prompt_tokens", 0),
            completion_tokens=usage.get("completion_tokens", 0),
            decode_ms=elapsed_ms,
            ttft_ms=elapsed_ms,
            engine=self.name,
        )

    def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[str]:
        """True token streaming: ``stream: true`` ChatCompletions request,
        SSE ``data:`` chunks parsed incrementally (delta.content pieces).

        A thin NON-generator wrapper (ADVICE r4): the readiness check and
        the ``_inflight`` increment run at CALL time, so a stream that has
        been created but not yet iterated when ``stop(drain_secs)`` fires
        is already visible to the drain — the httpx client can't be closed
        under it. A stream that is created but NEVER iterated would leak
        the increment permanently (an unstarted async generator's body —
        and its ``finally`` — never runs, even on aclose/GC), so a GC
        finalizer releases the slot for exactly that case."""
        if self._client is None or not self.api_key or self._draining:
            raise EngineUnavailable("OpenAI engine not initialized (missing key?)"
                                    if not self._draining else
                                    "engine draining")
        self._inflight += 1
        started = {"flag": False}
        agen = self._generate_stream_impl(
            started, prompt, max_tokens=max_tokens, temperature=temperature,
            timeout=timeout)
        weakref.finalize(agen, self._release_unstarted, started)
        return agen

    def _release_unstarted(self, started: dict) -> None:
        # Runs at the stream generator's GC. If the body ever started, its
        # own finally released the slot; otherwise do it here.
        if not started["flag"]:
            self._inflight -= 1

    async def _generate_stream_impl(
        self,
        started: dict,
        prompt: str,
        *,
        max_tokens: int,
        temperature: float,
        timeout: Optional[float],
    ) -> AsyncIterator[str]:
        started["flag"] = True
        try:
            async with self._client.stream(
                "POST",
                "/chat/completions",
                json={
                    "model": self.model,
                    "messages": [{"role": "user", "content": prompt}],
                    "temperature": temperature,
                    "max_tokens": max_tokens,
                    "stream": True,
                },
                timeout=timeout or self.timeout,
            ) as resp:
                if resp.status_code >= 400:
                    body = (await resp.aread()).decode(errors="replace")
                    raise EngineUnavailable(
                        f"upstream returned {resp.status_code}: {body[:200]}"
                    )
                ctype = resp.headers.get("content-type", "")
                if "text/event-stream" not in ctype:
                    # Upstream ignored stream:true (minimal OpenAI-compat
                    # stubs, the OPENAI_BASE_URL escape hatch): fall back to
                    # the one-shot completion body.
                    data = json.loads(await resp.aread())
                    text = data["choices"][0]["message"]["content"]
                    if text:
                        yield text
                    return
                async for line in resp.aiter_lines():
                    line = line.strip()
                    if not line.startswith("data:"):
                        continue  # comments / blank keep-alives
                    data = line[len("data:"):].strip()
                    if data == "[DONE]":
                        break
                    try:
                        choices = json.loads(data).get("choices", [])
                    except json.JSONDecodeError:
                        continue  # tolerate malformed keep-alive frames
                    if not choices:
                        continue
                    piece = (choices[0].get("delta") or {}).get("content")
                    if piece:
                        yield piece
        except httpx.TimeoutException as e:
            raise GenerationTimeout(str(e)) from e
        except httpx.HTTPError as e:
            # ConnectError before the stream opens, ReadError/protocol
            # errors mid-stream: surface as EngineUnavailable so callers
            # keying fallback on engine exception types catch them, matching
            # the initialization and >=400 paths above.
            raise EngineUnavailable(f"upstream stream failed: {e}") from e
        finally:
            self._inflight -= 1
