"""OpenAI-compatible remote engine (reference parity path).

Re-implements the reference's LangChain ``ChatOpenAI`` call
(app.py:106-122, 183-186) as a direct httpx ChatCompletions client, for
BASELINE config 1 and for pointing at any local OpenAI-compatible stub
server (the reference's ``OPENAI_BASE_URL`` escape hatch, app.py:114-115).

temperature=0 default matches app.py:109.
"""

from __future__ import annotations

import time
from typing import AsyncIterator, Optional

import httpx

from .protocol import EngineResult, EngineUnavailable, GenerationTimeout


class OpenAICompatEngine:
    name = "openai"

    def __init__(
        self,
        api_key: Optional[str],
        model: str = "gpt-3.5-turbo",
        base_url: Optional[str] = None,
        timeout: float = 60.0,
    ):
        self.api_key = api_key
        self.model = model
        self.base_url = (base_url or "https://api.openai.com/v1").rstrip("/")
        self.timeout = timeout
        self._client: Optional[httpx.AsyncClient] = None

    @property
    def ready(self) -> bool:
        return self._client is not None and bool(self.api_key)

    async def start(self) -> None:
        headers = {}
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        self._client = httpx.AsyncClient(
            base_url=self.base_url, headers=headers, timeout=self.timeout
        )

    async def stop(self) -> None:
        if self._client is not None:
            await self._client.aclose()
            self._client = None

    async def generate(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> EngineResult:
        if self._client is None or not self.api_key:
            raise EngineUnavailable("OpenAI engine not initialized (missing key?)")
        t0 = time.monotonic()
        try:
            resp = await self._client.post(
                "/chat/completions",
                json={
                    "model": self.model,
                    "messages": [{"role": "user", "content": prompt}],
                    "temperature": temperature,
                    "max_tokens": max_tokens,
                },
                timeout=timeout or self.timeout,
            )
        except httpx.TimeoutException as e:
            raise GenerationTimeout(str(e)) from e
        resp.raise_for_status()
        data = resp.json()
        text = data["choices"][0]["message"]["content"]
        usage = data.get("usage", {})
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        return EngineResult(
            text=text,
            prompt_tokens=usage.get("prompt_tokens", 0),
            completion_tokens=usage.get("completion_tokens", 0),
            decode_ms=elapsed_ms,
            ttft_ms=elapsed_ms,
            engine=self.name,
        )

    async def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[str]:
        result = await self.generate(
            prompt, max_tokens=max_tokens, temperature=temperature, timeout=timeout
        )
        yield result.text
