"""FallbackEngine — deterministic rule-based degradation path.

When the JAX engine is failing (circuit breaker open, watchdog trip,
repeated EngineUnavailable) and ``DEGRADED_FALLBACK=true``, the service
routes queries here instead of hard-failing with 503: a curated
pattern→command table answers the common read-only queries the reference
service was mostly used for, and anything unmatched degrades to the safe
``kubectl get all``. Responses are marked ``degraded: true`` with
``engine_metadata.engine == "fallback-rules"`` so clients and dashboards
can tell a rule hit from a real generation.

These rules were born as FakeEngine's test table (engine/fake.py) and are
promoted here as the production fallback; FakeEngine now imports them so
the two can never drift.
"""

from __future__ import annotations

import re
import time
from typing import AsyncIterator, Optional

from .protocol import EngineResult

#: Read-only pattern → command template; groups feed ``str.format``. The
#: DEGRADED fallback serves ONLY these: a blind keyword match must never
#: mint a mutating command ("why did the autoscaler delete pod web-1?"
#: must not answer "kubectl delete pod web-1") — without the LLM's
#: contextual judgment, degraded mode is strictly observational.
READ_ONLY_RULES = [
    (re.compile(r"\b(list|get|show)\b.*\bpods?\b", re.I), "kubectl get pods"),
    (re.compile(r"\b(list|get|show)\b.*\bnodes?\b", re.I), "kubectl get nodes"),
    (re.compile(r"\b(list|get|show)\b.*\b(deployments?|deploys?)\b", re.I),
     "kubectl get deployments"),
    (re.compile(r"\b(list|get|show)\b.*\bservices?\b", re.I), "kubectl get services"),
    (re.compile(r"\b(list|get|show)\b.*\bnamespaces?\b", re.I), "kubectl get namespaces"),
    (re.compile(r"\blogs?\b.*?(?:\bof\b|\bfor\b|\bfrom\b)\s+(\S+)", re.I),
     "kubectl logs {0}"),
    (re.compile(r"\bdescribe\b.*\bpod\b\s+(\S+)", re.I), "kubectl describe pod {0}"),
]

#: Mutating rules: part of FakeEngine's test table (the reference
#: service's full query surface) but never served by the fallback.
MUTATING_RULES = [
    (re.compile(r"\bdelete\b.*\bpod\b\s+(\S+)", re.I), "kubectl delete pod {0}"),
    (re.compile(r"\bscale\b.*\bdeployment\b\s+(\S+).*?\b(\d+)\b", re.I),
     "kubectl scale deployment {0} --replicas={1}"),
]

#: FakeEngine's full table (tests exercise mutating commands too).
RULES = READ_ONLY_RULES + MUTATING_RULES

_QUERY_RE = re.compile(
    r"User Request:\s*(.*?)\s*(?:\nKubectl Command:|\Z)", re.S
)


def extract_query(prompt: str) -> str:
    """Recover the user query from a rendered prompt (engine/prompts.py
    renders "...User Request: <query>\\nKubectl Command:")."""
    m = _QUERY_RE.search(prompt)
    return m.group(1) if m else prompt


def rule_command(query: str, rules=RULES) -> str:
    """First matching rule's command, or the safe catch-all."""
    for pattern, template in rules:
        hit = pattern.search(query)
        if hit:
            return template.format(*hit.groups())
    return "kubectl get all"


class FallbackEngine:
    """Engine-protocol implementation over the rule table.

    Always ready, never fails, sub-millisecond: the whole point is that
    this path has none of the real engine's failure modes.
    """

    name = "fallback-rules"

    def __init__(self) -> None:
        self._ready = True
        self.calls = 0

    @property
    def ready(self) -> bool:
        return self._ready

    async def start(self) -> None:
        self._ready = True

    async def stop(self, drain_secs: float = 0.0) -> None:
        self._ready = False

    async def generate(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> EngineResult:
        t0 = time.monotonic()
        self.calls += 1
        # Read-only rules only: degraded mode never mints a mutation.
        text = rule_command(extract_query(prompt), rules=READ_ONLY_RULES)
        elapsed_ms = (time.monotonic() - t0) * 1000.0
        return EngineResult(
            text=text,
            prompt_tokens=len(prompt.split()),
            completion_tokens=len(text.split()),
            decode_ms=elapsed_ms,
            ttft_ms=elapsed_ms,
            engine=self.name,
        )

    async def generate_stream(
        self,
        prompt: str,
        *,
        max_tokens: int = 128,
        temperature: float = 0.0,
        timeout: Optional[float] = None,
    ) -> AsyncIterator[str]:
        result = await self.generate(
            prompt, max_tokens=max_tokens, temperature=temperature,
            timeout=timeout,
        )
        yield result.text
